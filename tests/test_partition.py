"""State-partitioner subsystem tests (tpu_resnet/parallel/partition.py +
zero.py): the ZeRO-1 rule set, zero1-vs-replicated step parity on the
8-device fakepod, the cross-partition restore contract, and the golden
memory-budget acceptance gate — the mesh8 zero1 twin's optimizer-slot
argument bytes must stay ≤ 0.15x the replicated twin's with donation
intact (arXiv:2004.13336's ~1/8 cut, regression-locked)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resnet import parallel
from tpu_resnet.config import load_config
from tpu_resnet.data import pipeline
from tpu_resnet.models import build_model
from tpu_resnet.parallel.partition import (StatePartitioner,
                                           ZERO1_SMALL_LEAF_BYTES,
                                           check_partition_mode)
from tpu_resnet.train import build_schedule
from tpu_resnet.train.state import init_partitioned_state
from tpu_resnet.train.step import (check_step_config, make_train_step,
                                   shard_step)

P = jax.sharding.PartitionSpec

ANALYSIS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tpu_resnet", "analysis")


def _mesh(n=8, partition="replicated"):
    cfg = load_config("smoke")
    cfg.mesh.data = n
    cfg.mesh.partition = partition
    return cfg, parallel.create_mesh(cfg.mesh,
                                     devices=jax.devices()[:n])


# ------------------------------------------------------------- rule set
def test_partition_mode_validation():
    assert check_partition_mode("replicated") == "replicated"
    assert check_partition_mode("zero1") == "zero1"
    with pytest.raises(ValueError, match="mesh.partition must be one of"):
        check_partition_mode("zero2")  # a typo must not mean 'replicated'
    cfg, mesh = _mesh(8)
    assert parallel.make_partitioner(cfg.mesh, mesh).mode == "replicated"
    assert parallel.make_partitioner(None, mesh).mode == "replicated"


def test_zero1_slot_spec_rules():
    """The per-leaf rule: scalars and small indivisible leaves stay
    replicated, everything else shards on its FIRST data-divisible axis,
    a LARGE indivisible leaf is a validation error naming the leaf."""
    _, mesh = _mesh(8)
    part = StatePartitioner(mesh, "zero1")
    assert part.is_sharded
    assert part.slot_spec(()) == P()                      # step counts
    assert part.slot_spec((16, 16)) == P("data")          # first axis wins
    assert part.slot_spec((3, 3, 16, 16)) == P(None, None, "data")
    assert part.slot_spec((10,)) == P()                   # small head bias
    big = ZERO1_SMALL_LEAF_BYTES  # (bytes/4 floats) * 4B > threshold, odd
    assert part.slot_spec((big + 1,), nbytes=4 * (big + 1)) is None

    class FakeState:
        def __init__(self, opt):
            self.step = jnp.zeros((), jnp.int32)
            self.params = {}
            self.batch_stats = {}
            self.opt_state = opt

        def replace(self, **kw):
            out = FakeState(kw.get("opt_state", self.opt_state))
            out.__dict__.update({k: v for k, v in kw.items()})
            return out

    bad = FakeState({"huge_odd": jax.ShapeDtypeStruct((100003,),
                                                      jnp.float32)})
    with pytest.raises(ValueError) as e:
        part.validate(bad)
    msg = str(e.value)
    assert "huge_odd" in msg and "100003" in msg and "8-way" in msg


def test_zero1_is_identity_on_1way_data_axis():
    """zero1 over a 1-way data axis must take the replicated path
    everywhere (is_sharded False → plain optax chain, replicated
    placement) — pinned structurally here and as the config-matrix
    same_program_as twin (cifar10_rn8_f32_zero1_mesh1)."""
    import optax

    from tpu_resnet.parallel import zero

    _, mesh = _mesh(1, partition="zero1")
    part = StatePartitioner(mesh, "zero1")
    assert not part.is_sharded
    tx = optax.sgd(0.1, momentum=0.9)
    grads = {"w": jnp.ones((8, 4))}
    opt = tx.init(grads)
    plain = zero.make_update_fn(tx, None)
    ident = zero.make_update_fn(tx, part)
    j1 = str(jax.make_jaxpr(plain)(grads, opt, grads))
    j2 = str(jax.make_jaxpr(ident)(grads, opt, grads))
    assert j1 == j2


# --------------------------------------------------- fakepod step parity
def _build(partition, n=8, batch=16):
    cfg = load_config("smoke")
    cfg.data.dataset = "synthetic"
    cfg.model.name = "mlp"
    cfg.train.global_batch_size = batch
    cfg.mesh.data = n
    cfg.mesh.partition = partition
    mesh = parallel.create_mesh(cfg.mesh, devices=jax.devices()[:n])
    check_step_config(cfg, mesh.shape["data"])
    part = parallel.make_partitioner(cfg.mesh, mesh)
    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    state = init_partitioned_state(model, cfg.optim, sched,
                                   jax.random.PRNGKey(0),
                                   jnp.zeros((1, 32, 32, 3)), part)
    base = make_train_step(model, cfg.optim, sched, 10, None,
                           base_rng=jax.random.PRNGKey(1), mesh=mesh,
                           partitioner=part)
    fn = shard_step(base, mesh,
                    state_sharding=(part.state_shardings(state)
                                    if part.is_sharded else None))
    return cfg, mesh, part, state, fn


def test_zero1_replicated_step_parity_on_fakepod():
    """zero1 and replicated must produce bit-identical loss streams and
    parameters within 1e-6 over real steps on the 8-device fakepod —
    sharding the weight update changes WHERE math runs, never what it
    computes (the documented tolerance covers reduce-scatter reduction-
    order drift; observed bit-identical on this backend)."""
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (3, 16, 32, 32, 3)).astype(np.uint8)
    labs = rng.integers(0, 10, (3, 16)).astype(np.int32)
    out = {}
    for partition in ("replicated", "zero1"):
        _, mesh, part, state, fn = _build(partition)
        bs = parallel.batch_sharding(mesh)
        losses = []
        for i in range(3):
            gi, gl = pipeline.to_global_arrays((imgs[i], labs[i]), bs)
            state, m = fn(state, gi, gl)
            losses.append(float(jax.device_get(m["loss"])))
        out[partition] = (losses, jax.device_get(state))
        if partition == "zero1":
            # The slots genuinely live sharded: the hidden-layer momentum
            # carries a 'data' spec, the small head bias stays replicated.
            specs = {
                tuple(leaf.shape): leaf.sharding.spec
                for leaf in jax.tree_util.tree_leaves(state.opt_state)
                if hasattr(leaf, "sharding")}
            assert any("data" in str(s) for s in specs.values()), specs
            assert specs.get((10,)) == P()
    l_rep, s_rep = out["replicated"]
    l_z, s_z = out["zero1"]
    assert l_rep == l_z  # loss stream bit-identical on this backend
    for a, b in zip(jax.tree_util.tree_leaves(s_rep.params),
                    jax.tree_util.tree_leaves(s_z.params)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_state_argument_bytes_breakdown():
    """The analytic per-component breakdown the ledger/goldens record:
    zero1 cuts ONLY the optimizer slots; params and BN stats stay
    replicated (the forward/backward sees gathered weights)."""
    _, _, part_r, state, _ = _build("replicated")
    rep = part_r.state_argument_bytes(state)
    _, _, part_z, state_z, _ = _build("zero1")
    z = part_z.state_argument_bytes(state_z)
    assert z["params_argument_bytes"] == rep["params_argument_bytes"]
    assert z["batch_stats_argument_bytes"] == \
        rep["batch_stats_argument_bytes"]
    assert 0 < z["opt_state_argument_bytes"] \
        < 0.3 * rep["opt_state_argument_bytes"]


# --------------------------------------------------- restore contracts
def test_partitioned_template_is_abstract_and_sharded():
    from tpu_resnet.train.checkpoint import partitioned_template

    cfg, mesh = _mesh(8, partition="zero1")
    cfg.model.name = "mlp"
    cfg.data.dataset = "synthetic"
    template = partitioned_template(cfg, mesh)
    leaves = jax.tree_util.tree_leaves(template)
    assert all(isinstance(x, jax.ShapeDtypeStruct) for x in leaves)
    opt_specs = [x.sharding.spec
                 for x in jax.tree_util.tree_leaves(template.opt_state)]
    assert any("data" in str(s) for s in opt_specs)
    # params replicated for the forward — every partition mode
    assert all(s == P() for s in
               (x.sharding.spec
                for x in jax.tree_util.tree_leaves(template.params)))


def test_cross_partition_restore_reshards_never_corrupts(tmp_path):
    """A checkpoint saved under one partition restores under the other
    with identical global values — orbax stores global logical arrays,
    so a cross-partition restore is an explicit reshard into the
    template's layout, never a silent corruption (docs/PARALLELISM.md
    restore-compat matrix)."""
    from tpu_resnet.train.checkpoint import (CheckpointManager,
                                             partitioned_template)

    cfg, mesh, part, state, fn = _build("zero1")
    rng = np.random.default_rng(3)
    bs = parallel.batch_sharding(mesh)
    gi, gl = pipeline.to_global_arrays(
        (rng.integers(0, 255, (16, 32, 32, 3)).astype(np.uint8),
         rng.integers(0, 10, 16).astype(np.int32)), bs)
    state, _ = fn(state, gi, gl)  # non-trivial momentum in the slots
    ckpt = CheckpointManager(str(tmp_path))
    ckpt.save(1, state)
    ckpt.wait()
    for target in ("replicated", "zero1"):
        t_cfg = load_config("smoke")
        t_cfg.data.dataset = "synthetic"
        t_cfg.model.name = "mlp"
        t_cfg.train.global_batch_size = 16
        t_cfg.mesh.data = 8
        t_cfg.mesh.partition = target
        template = partitioned_template(t_cfg, mesh)
        restored = ckpt.restore(template, step=1)
        for want, got in zip(jax.tree_util.tree_leaves(
                jax.device_get(state)),
                jax.tree_util.tree_leaves(jax.device_get(restored))):
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(got))
    ckpt.close()


# ------------------------------------------------ golden acceptance gate
def test_golden_memory_zero1_twin_gate():
    """THE acceptance artifact: analysis/golden_memory.json must carry
    the mesh8 replicated/zero1 twin where the zero1 optimizer-slot
    argument bytes are ≤ 0.15x the replicated twin's (≈1/8 + slack) with
    the donation credit intact on both — a PR that voids the ZeRO-1
    memory win (or breaks donation under it) fails this gate until the
    goldens are intentionally regenerated."""
    with open(os.path.join(ANALYSIS_DIR, "golden_memory.json")) as f:
        entries = json.load(f)["entries"]
    rep = entries["cifar10_rn8_f32_mesh8"]
    z = entries["cifar10_rn8_f32_mesh8_zero1"]
    assert z["partition"] == "zero1"
    assert z["opt_state_argument_bytes"] <= \
        0.15 * rep["opt_state_argument_bytes"]
    # no alias collapse: donation still credits the sharded slots
    assert rep["alias_bytes"] > 0 and z["alias_bytes"] > 0
    # the cut shows up in XLA's own aggregate too, not just our analytic
    assert z["argument_bytes"] < rep["argument_bytes"]
    # params stay replicated — zero1 must not have quietly sharded them
    assert z["params_argument_bytes"] == rep["params_argument_bytes"]


def test_golden_jaxprs_pin_zero1_entries():
    with open(os.path.join(ANALYSIS_DIR, "golden_jaxprs.json")) as f:
        entries = json.load(f)["entries"]
    for name in ("cifar10_rn8_f32_mesh8_zero1",
                 "imagenet_rn18_bf16_mesh8_zero1",
                 "cifar10_rn8_f32_zero1_mesh1"):
        assert name in entries, f"golden jaxpr missing for {name}"


def test_sweep_space_has_partition_axis():
    from tpu_resnet.tools.sweep import DEFAULT_SPACE

    assert DEFAULT_SPACE["partition"][0] == "replicated"  # base point
    assert "zero1" in DEFAULT_SPACE["partition"]


def test_zero1_rejects_per_replica_bn():
    cfg = load_config("smoke")
    cfg.mesh.partition = "zero1"
    cfg.model.sync_bn = False
    with pytest.raises(ValueError, match="sync_bn"):
        check_step_config(cfg, 8)
    check_step_config(cfg, 1)  # 1-way axis: per-replica BN is moot


# --------------------------------------------------------- slow drills
@pytest.mark.slow  # several in-process train() runs (~60s)
def test_zero1_train_resume_parity_and_restore_consumers(tmp_path):
    """Partition-parity across a REAL resume boundary, then both
    read-only consumers on the zero1 checkpoint: the replicated
    straight-through run and the zero1 preempt-at-4/resume-to-8 run must
    log loss streams equal within 1e-6 at the same steps, and the
    evaluator-template restore and the serve CheckpointBackend must
    produce argmax-identical predictions from the zero1 checkpoint."""
    from tpu_resnet.serve.backend import CheckpointBackend
    from tpu_resnet.serve.infer import make_serve_infer
    from tpu_resnet.train.checkpoint import (CheckpointManager,
                                             partitioned_template)
    from tpu_resnet.train.loop import train

    def _cfg(partition, train_dir):
        cfg = load_config("smoke")
        cfg.data.dataset = "synthetic"
        cfg.data.device_resident = "off"
        cfg.data.transfer_stage = 1
        cfg.model.name = "mlp"
        cfg.train.global_batch_size = 16
        cfg.train.train_steps = 8
        cfg.train.log_every = 2
        cfg.train.summary_every = 2
        cfg.train.checkpoint_every = 4
        cfg.train.image_summary_every = 0
        cfg.train.steps_per_call = 1
        cfg.train.telemetry_port = -1
        cfg.mesh.data = 8
        cfg.mesh.partition = partition
        cfg.train.train_dir = str(train_dir)
        return cfg

    def _losses(train_dir):
        out = {}
        with open(os.path.join(str(train_dir), "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if "loss" in rec:
                    out[rec["step"]] = rec["loss"]
        return out

    rep_cfg = _cfg("replicated", tmp_path / "rep")
    train(rep_cfg)
    z_cfg = _cfg("zero1", tmp_path / "zero1")
    train(z_cfg, max_steps=4)   # stop at the checkpoint boundary
    train(z_cfg)                # resume 4 -> 8 from the zero1 checkpoint
    l_rep, l_z = _losses(tmp_path / "rep"), _losses(tmp_path / "zero1")
    assert set(l_rep) == set(l_z) == {2, 4, 6, 8}
    for step in sorted(l_rep):
        assert l_rep[step] == pytest.approx(l_z[step], rel=1e-6,
                                            abs=1e-6), step

    # Both restore consumers on the zero1 checkpoint.
    mesh = parallel.create_mesh(z_cfg.mesh,
                                devices=jax.devices()[:8])
    template = partitioned_template(z_cfg, mesh)
    ckpt = CheckpointManager(z_cfg.train.train_dir)
    state = ckpt.restore(template)
    ckpt.close()
    rng = np.random.default_rng(7)
    images = rng.integers(0, 255, (4, 32, 32, 3)).astype(np.uint8)
    infer = make_serve_infer(z_cfg)
    eval_logits = np.asarray(infer({"params": state.params,
                                    "batch_stats": state.batch_stats},
                                   jnp.asarray(images)))
    backend = CheckpointBackend(z_cfg, mesh=mesh)
    serve_logits = backend.infer(images)
    backend.close()
    np.testing.assert_array_equal(eval_logits.argmax(-1),
                                  serve_logits.argmax(-1))
    assert backend.model_step == 8
