import json

import pytest

from tpu_resnet.config import PRESETS, RunConfig, load_config


def test_default_roundtrip():
    cfg = RunConfig()
    d = json.loads(cfg.to_json())
    cfg2 = RunConfig.from_dict(d)
    assert cfg2.to_dict() == cfg.to_dict()


def test_presets_build():
    for name in PRESETS:
        cfg = load_config(name)
        assert cfg.data.num_classes > 0


def test_cifar_preset_matches_reference_recipe():
    # README.md:28 local config: batch 128, piecewise LR, wd 2e-4.
    cfg = load_config("cifar10")
    assert cfg.train.global_batch_size == 128
    assert cfg.optim.schedule == "cifar_piecewise"
    assert cfg.optim.weight_decay == pytest.approx(2e-4)


def test_imagenet_preset_matches_intel_caffe_recipe():
    # resnet_imagenet_train.py:236-260 + submit_imagenet_daint_dist.sh:38-40.
    cfg = load_config("imagenet")
    assert cfg.train.global_batch_size == 1024
    assert cfg.train.train_steps == 112_600
    assert cfg.optim.weight_decay == pytest.approx(1e-4)
    assert cfg.optim.warmup_steps == 6240


def test_overrides():
    cfg = load_config("smoke", overrides=[
        "train.train_steps=7", "model.compute_dtype=bfloat16",
        "data.use_native_loader=false"])
    assert cfg.train.train_steps == 7
    assert cfg.model.compute_dtype == "bfloat16"
    assert cfg.data.use_native_loader is False


def test_bad_override_rejected():
    with pytest.raises(ValueError):
        load_config("smoke", overrides=["train.nope=1"])
    with pytest.raises(ValueError):
        load_config("smoke", overrides=["no_equals"])


def test_unknown_preset():
    with pytest.raises(ValueError):
        load_config("nope")
