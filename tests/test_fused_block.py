"""ops/fused_block.py — interpret-mode correctness of the experimental
fused v2 basic-block forward vs the XLA reference (its first TPU run
happens unattended in battery stage 05_fused_block_ab; this keeps that from being its
first run ever)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resnet.ops.fused_block import block_fwd, block_fwd_reference


def _params(c, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda *s: jnp.asarray(rng.normal(size=s) * 0.1, dtype)
    return (mk(3, 3, c, c), mk(3, 3, c, c),
            jnp.asarray(rng.uniform(0.5, 1.5, c), dtype),
            mk(c), jnp.asarray(rng.uniform(0.5, 1.5, c), dtype), mk(c))


@pytest.mark.parametrize("b,hw,c,bt", [(4, 8, 16, 2), (2, 8, 32, 2),
                                       (8, 4, 16, 8)])
def test_fused_block_matches_reference(b, hw, c, bt):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(b, hw, hw, c)), jnp.float32)
    params = _params(c)
    got = block_fwd(x, *params, batch_tile=bt, interpret=True)
    want = block_fwd_reference(x, *params)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_block_bf16_io():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 16)), jnp.bfloat16)
    params = _params(16, dtype=jnp.bfloat16)
    got = block_fwd(x, *params, batch_tile=2, interpret=True)
    want = block_fwd_reference(x, *params)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(got.astype(np.float32),
                               want.astype(np.float32), rtol=5e-2,
                               atol=5e-2)


def test_fused_block_rejects_ragged_tile():
    x = jnp.zeros((6, 4, 4, 16))
    with pytest.raises(ValueError, match="not divisible"):
        block_fwd(x, *_params(16), batch_tile=4, interpret=True)


def test_ab_harness_tiny(tmp_path, monkeypatch):
    """The battery-stage-05_fused_block_ab harness runs unattended on a live window;
    drive its exact code path at tiny config first (same pattern as
    tests/test_streaming_gap_probe.py)."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import fused_block_ab

    out = tmp_path / "ab.json"
    monkeypatch.setattr(fused_block_ab, "SHAPES",
                    [(8, 8, 8, 16, 4, 2)])
    monkeypatch.setattr(sys, "argv", [
        "fused_block_ab.py", "--length", "2", "--reps", "1",
        "--dtype", "float32", "--out", str(out)])
    fused_block_ab.main()
    got = json.load(open(out))["by_shape"]["b8_8x8x16"]
    for direction in ("fwd", "fwd_bwd", "train_fwd_live_bn",
                      "train_fwd_bwd_live_bn"):
        e = got[direction]
        assert e["pallas_us_per_block"] > 0 and e["xla_us_per_block"] > 0


def test_block_apply_grads_match_reference():
    """Custom-VJP fused block (Pallas fwd + Pallas bwd with in-kernel
    activation recompute) vs jax.grad of the XLA reference — every input
    and parameter gradient, including across batch tiles (b=4, bt=2
    exercises the sequential-grid accumulation)."""
    from tpu_resnet.ops.fused_block import block_apply

    rng = np.random.default_rng(5)
    c = 16
    x = jnp.asarray(rng.normal(size=(4, 8, 8, c)), jnp.float32)
    params = _params(c, seed=6)

    def loss_fused(x, *p):
        return jnp.sum(block_apply(x, *p, 2, True, 2) ** 2)

    def loss_ref(x, *p):
        return jnp.sum(block_fwd_reference(x, *p) ** 2)

    got = jax.grad(loss_fused, argnums=tuple(range(7)))(x, *params)
    want = jax.grad(loss_ref, argnums=tuple(range(7)))(x, *params)
    names = ("dx", "dw1", "dw2", "ds1", "db1", "ds2", "db2")
    for name, g, w in zip(names, got, want):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-4,
                                   err_msg=name)


def test_block_apply_value_matches_fwd():
    from tpu_resnet.ops.fused_block import block_apply, block_fwd

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 16)), jnp.float32)
    params = _params(16, seed=8)
    np.testing.assert_allclose(
        block_apply(x, *params, 2, True, 2),
        block_fwd(x, *params, batch_tile=2, interpret=True), rtol=0,
        atol=0)


def test_block_train_fwd_matches_reference():
    """Two-pass live-batch-stats block (stats kernel + folded apply) vs
    the XLA training-BN oracle: output and all four returned moments,
    across batch tiles."""
    from tpu_resnet.ops.fused_block import (block_train_fwd,
                                            block_train_fwd_reference)

    rng = np.random.default_rng(9)
    c = 16
    x = jnp.asarray(rng.normal(size=(4, 8, 8, c)) * 2 + 1, jnp.float32)
    gb = lambda lo, hi: jnp.asarray(rng.uniform(lo, hi, c), jnp.float32)
    args = (jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.2, jnp.float32),
            jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.2, jnp.float32),
            gb(0.5, 1.5), gb(-0.3, 0.3), gb(0.5, 1.5), gb(-0.3, 0.3))

    y, moms = block_train_fwd(x, *args, batch_tile=2, interpret=True)
    y_ref, moms_ref = block_train_fwd_reference(x, *args)
    np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-4)
    for name, m, mr in zip(("mean1", "var1", "mean2", "var2"),
                           moms, moms_ref):
        np.testing.assert_allclose(m, mr, rtol=1e-4, atol=1e-5,
                                   err_msg=name)


def test_block_train_apply_grads_match_reference():
    """Training-path custom VJP (three-pass Pallas backward with the BN
    batch-moment correction terms) vs jax.grad of the live-BN XLA
    oracle — all seven gradients, across batch tiles."""
    from tpu_resnet.ops.fused_block import (block_train_apply,
                                            block_train_fwd_reference)

    rng = np.random.default_rng(11)
    c = 16
    x = jnp.asarray(rng.normal(size=(4, 8, 8, c)) * 2 + 1, jnp.float32)
    gb = lambda lo, hi: jnp.asarray(rng.uniform(lo, hi, c), jnp.float32)
    args = (jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.2, jnp.float32),
            jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.2, jnp.float32),
            gb(0.5, 1.5), gb(-0.3, 0.3), gb(0.5, 1.5), gb(-0.3, 0.3))

    def loss_fused(x, *p):
        y, _moms = block_train_apply(x, *p, 1e-5, 2, True)
        return jnp.sum(y ** 2)

    def loss_ref(x, *p):
        y, _moms = block_train_fwd_reference(x, *p)
        return jnp.sum(y ** 2)

    got = jax.grad(loss_fused, argnums=tuple(range(7)))(x, *args)
    want = jax.grad(loss_ref, argnums=tuple(range(7)))(x, *args)
    names = ("dx", "dw1", "dw2", "dgamma1", "dbeta1", "dgamma2", "dbeta2")
    for name, g, w in zip(names, got, want):
        np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-4,
                                   err_msg=name)


def test_bwd_tile_defaults_divide_odd_batches():
    """Review regression: a batch the forward accepts (b=12, tile=16 ->
    bt=12) must not crash at jax.grad time when the backward halves the
    tile (16//2=8 does not divide 12; the default picks a divisor)."""
    from tpu_resnet.ops.fused_block import block_apply

    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(12, 4, 4, 16)), jnp.float32)
    params = _params(16, seed=14)
    g = jax.grad(
        lambda x: jnp.sum(block_apply(x, *params, 16, True, None) ** 2))(x)
    assert np.isfinite(np.asarray(g)).all()
