"""Native C++ loader tests — cross-validated bit-for-bit against the pure
Python implementations (the contract that lets either path serve traffic)."""

import numpy as np
import pytest

from tpu_resnet.data import tfrecord


@pytest.fixture(scope="module")
def native():
    try:
        from tpu_resnet.native import build
        build.build()
        from tpu_resnet.native import available, loader
    except Exception as e:  # no compiler in some environments
        pytest.skip(f"native loader unavailable: {e}")
    if not available():
        pytest.skip("native loader not built")
    return loader


def test_crc32c_matches_python(native):
    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 8, 9, 64, 1000, 4097):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert native.crc32c(data) == tfrecord.crc32c(data)


def test_fixed_records_match_numpy(native, tmp_path):
    rng = np.random.default_rng(1)
    recs = rng.integers(0, 256, (100, 3073), dtype=np.uint8)
    f1 = str(tmp_path / "a.bin")
    f2 = str(tmp_path / "b.bin")
    recs[:60].tofile(f1)
    recs[60:].tofile(f2)
    out = native.read_fixed_length_records([f1, f2], 3073)
    np.testing.assert_array_equal(out, recs)


def test_fixed_records_bad_size(native, tmp_path):
    f = str(tmp_path / "bad.bin")
    open(f, "wb").write(b"x" * 100)
    with pytest.raises(ValueError):
        native.read_fixed_length_records([f], 3073)


def test_tfrecord_split_matches_python(native, tmp_path):
    path = str(tmp_path / "t.tfrecord")
    payloads = [b"abc", b"", b"x" * 5000, bytes(range(256))]
    tfrecord.write_records(path, payloads)
    assert native.tfrecord_payloads(path, verify_crc=True) == payloads
    assert list(tfrecord.read_records(path, verify_crc=True)) == payloads


def test_tfrecord_corruption_detected(native, tmp_path):
    path = str(tmp_path / "t.tfrecord")
    tfrecord.write_records(path, [b"payload-one", b"payload-two"])
    data = bytearray(open(path, "rb").read())
    data[14] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError):
        native.tfrecord_payloads(path, verify_crc=True)
    with pytest.raises(ValueError):
        list(tfrecord.read_records(path, verify_crc=True))


def test_jpeg_decode_matches_pil(native, tmp_path):
    """Native libjpeg decode+resize+crop tracks the PIL path within
    rounding (same random draws → interchangeable per image)."""
    from tpu_resnet.native import jpeg_available

    if not jpeg_available():
        pytest.skip("built without libjpeg")
    import io

    from PIL import Image

    from tpu_resnet.data import imagenet as inet

    rng0 = np.random.default_rng(0)
    img = (rng0.random((96, 128, 3)) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=95)
    jpeg = buf.getvalue()

    for train in (False, True):
        r1 = np.random.default_rng(7)
        r2 = np.random.default_rng(7)
        nat = inet.decode_and_crop(jpeg, train, r1, resize_min=72,
                                   resize_max=90, eval_resize=80,
                                   out_size=64, use_native=True)
        pil = inet.decode_and_crop(jpeg, train, r2, resize_min=72,
                                   resize_max=90, eval_resize=80,
                                   out_size=64, use_native=False)
        assert nat.shape == pil.shape == (64, 64, 3)
        diff = np.abs(nat.astype(int) - pil.astype(int))
        assert diff.max() <= 2, f"train={train}: max diff {diff.max()}"


def test_jpeg_decode_bad_input_returns_none(native):
    from tpu_resnet.native import jpeg_available, loader

    if not jpeg_available():
        pytest.skip("built without libjpeg")
    assert loader.decode_jpeg_vgg(b"not a jpeg", 256, 224) is None
