"""ImageNet pipeline tests on tiny generated JPEG shards
(format per reference resnet_imagenet_train.py:105-158)."""

import io

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image

from tpu_resnet.data import imagenet, tfrecord


def make_shards(tmp_path, n_shards=2, per_shard=6, train=True, size=(320, 280)):
    rng = np.random.default_rng(0)
    labels = []
    for s in range(n_shards):
        name = (f"train-{s:05d}-of-{n_shards:05d}" if train
                else f"validation-{s:05d}-of-{n_shards:05d}")
        records = []
        for i in range(per_shard):
            arr = rng.integers(0, 256, (size[1], size[0], 3), np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, "JPEG")
            label = int(rng.integers(1, 1001))  # shards are 1-based
            labels.append(label)
            records.append(tfrecord.encode_example({
                "image/encoded": [buf.getvalue()],
                "image/class/label": [label],
                "image/class/text": [b"dummy"],
            }))
        tfrecord.write_records(str(tmp_path / name), records)
    return labels


def test_shard_files_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        imagenet.shard_files(str(tmp_path), train=True)


def test_train_iterator_batches(tmp_path):
    make_shards(tmp_path, train=True)
    it = iter(imagenet.ImageNetIterator(str(tmp_path), local_batch=4,
                                        train=True, num_workers=2,
                                        shuffle_buffer=8))
    images, labels = next(it)
    assert images.shape == (4, 224, 224, 3)
    assert images.dtype == np.uint8
    assert labels.dtype == np.int32
    assert (labels >= 0).all() and (labels < 1000).all()  # 0-based output


def test_eval_examples_full_coverage_and_padding(tmp_path):
    want = make_shards(tmp_path, train=False, n_shards=2, per_shard=5)
    batches = list(imagenet.eval_examples(str(tmp_path), batch=4))
    assert len(batches) == 3  # 10 examples → 4+4+2(+2 pad)
    labels = np.concatenate([lab for _, lab in batches])
    valid = labels[labels >= 0]
    assert len(valid) == 10
    # 0-based labels match the 1-based shard labels
    assert sorted(valid.tolist()) == sorted(l - 1 for l in want)
    assert (labels[-2:] == -1).all()


def test_eval_examples_honors_eval_resize(tmp_path):
    """cfg.data.eval_resize must reach the decode (it used to be dropped:
    a 64px eval with the 256 default resized 4x too far and center-cropped
    ~6% of the image). With eval_resize == out_size the whole image
    survives; with a much larger resize side only the center does."""
    make_shards(tmp_path, train=False, n_shards=1, per_shard=1,
                size=(100, 100))
    def first(eval_resize):
        img, _ = next(iter(imagenet.eval_examples(
            str(tmp_path), batch=1, image_size=64,
            eval_resize=eval_resize)))
        return img[0]
    tight = first(64)     # resize side 64 → crop = whole image
    loose = first(256)    # resize side 256 → crop = center 25%
    assert not np.array_equal(tight, loose)


def test_iterator_native_planes_equivalent(tmp_path):
    """Both data planes must produce the same stream through the full
    iterator — the decoders are documented as interchangeable
    per-image."""
    from tpu_resnet.native import jpeg_available

    if not jpeg_available():  # same convention as tests/test_native.py
        pytest.skip("built without libjpeg — both paths would be PIL")
    make_shards(tmp_path, n_shards=2, per_shard=4, train=True)

    def batch(use_native):
        it = iter(imagenet.ImageNetIterator(
            str(tmp_path), local_batch=4, train=True, num_workers=1,
            shuffle_buffer=8, seed=1, use_native=use_native))
        return next(it)

    img_n, lab_n = batch(True)
    img_p, lab_p = batch(False)
    np.testing.assert_array_equal(lab_n, lab_p)
    # same parity contract as tests/test_native.py: libjpeg and PIL may
    # differ by rounding, never structurally
    diff = np.abs(img_n.astype(np.int16) - img_p.astype(np.int16))
    assert diff.max() <= 2, f"max diff {diff.max()}"


def test_use_native_loader_reaches_imagenet_chain(tmp_path, monkeypatch):
    """data.use_native_loader must flow from the config through
    train_batches and eval_split_batches (it used to stop at the CIFAR
    path)."""
    import tpu_resnet.data as data_lib
    from tpu_resnet.config import DataConfig

    make_shards(tmp_path, n_shards=2, per_shard=4, train=True)
    make_shards(tmp_path, n_shards=1, per_shard=2, train=False)
    cfg = DataConfig(dataset="imagenet", data_dir=str(tmp_path),
                     use_native_loader=False)

    seen = {}
    real_iter = imagenet.ImageNetIterator
    real_eval = imagenet.eval_examples

    def spy_iter(*a, **kw):
        seen["train"] = kw
        return real_iter(*a, **kw)

    def spy_eval(*a, **kw):
        seen["eval"] = kw
        return real_eval(*a, **kw)

    monkeypatch.setattr(data_lib.imagenet, "ImageNetIterator", spy_iter)
    monkeypatch.setattr(data_lib.imagenet, "eval_examples", spy_eval)

    next(data_lib.train_batches(cfg, local_batch=2))
    next(iter(data_lib.eval_split_batches(cfg, batch=2,
                                          process_index=0,
                                          process_count=1)))
    assert seen["train"]["use_native"] is False
    assert seen["eval"]["use_native"] is False


def test_decode_and_crop_train_and_eval():
    rng = np.random.default_rng(0)
    arr = np.zeros((300, 400, 3), np.uint8)
    arr[:, :, 0] = 255  # red image survives resize/crop
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=95)
    out = imagenet.decode_and_crop(buf.getvalue(), True, rng,
                                   resize_min=256, resize_max=320)
    assert out.shape == (224, 224, 3)
    assert out[:, :, 0].mean() > 200
    out_eval = imagenet.decode_and_crop(buf.getvalue(), False, rng)
    assert out_eval.shape == (224, 224, 3)


def test_grayscale_jpeg_converted_to_rgb():
    rng = np.random.default_rng(0)
    buf = io.BytesIO()
    Image.fromarray(np.full((260, 260), 128, np.uint8), "L").save(buf, "JPEG")
    out = imagenet.decode_and_crop(buf.getvalue(), False, rng)
    assert out.shape == (224, 224, 3)


def test_files_striped_across_processes(tmp_path):
    make_shards(tmp_path, n_shards=4, per_shard=2, train=True)
    a = imagenet.ImageNetIterator(str(tmp_path), 2, process_index=0,
                                  process_count=2)
    b = imagenet.ImageNetIterator(str(tmp_path), 2, process_index=1,
                                  process_count=2)
    assert set(a.files).isdisjoint(b.files)
    assert len(a.files) + len(b.files) == 4


def test_train_stream_resume_continues_exactly(tmp_path):
    """VERDICT round 1 item 7: a resumed ImageNet run must continue the
    record stream at the position an uninterrupted run would have reached,
    not restart from epoch 0. With one worker the batch assembly is
    deterministic, so label sequences must match batch-for-batch."""
    import itertools

    make_shards(tmp_path, n_shards=4, per_shard=8, train=True)

    def batches(start_step, n, verify=False):
        it = iter(imagenet.ImageNetIterator(
            str(tmp_path), local_batch=4, train=True, num_workers=1,
            shuffle_buffer=8, seed=3, start_step=start_step,
            verify_records=verify))
        return [lab.tolist() for _, lab in itertools.islice(it, n)]

    full = batches(0, 6)          # steps 0..5 uninterrupted
    resumed = batches(3, 3)       # restart "after step 3"
    assert resumed == full[3:6]
    # and the resumed stream is genuinely shuffled/advanced, not epoch 0
    assert resumed != full[0:3]
    # CRC verification covers the resume fast-forward path too
    assert batches(3, 3, verify=True) == full[3:6]


def test_verify_records_catches_corruption(tmp_path):
    """data.verify_records: a flipped payload byte must fail loudly
    instead of feeding a garbage JPEG downstream (native CRC path when
    built, python fallback otherwise)."""
    make_shards(tmp_path, n_shards=1, per_shard=4, train=True)
    shard = next(tmp_path.glob("train-*"))
    raw = bytearray(shard.read_bytes())
    raw[len(raw) // 2] ^= 0xFF  # corrupt one payload byte
    shard.write_bytes(bytes(raw))

    with pytest.raises(ValueError):
        list(imagenet.read_shard_records(str(shard), verify_crc=True))
    # without verification the corruption passes through silently
    assert len(list(imagenet.read_shard_records(str(shard)))) == 4
