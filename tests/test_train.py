"""Train-step and loop tests on the virtual 8-device mesh — the test the
reference never had for its distribution modes (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_resnet.config import load_config
from tpu_resnet.data.cifar import synthetic_data
from tpu_resnet.models import build_model
from tpu_resnet.parallel import batch_sharding, create_mesh, replicated
from tpu_resnet.train import (
    build_schedule,
    init_state,
    make_train_step,
    shard_step,
)
from tpu_resnet.train.step import l2_weight_penalty


def _setup(n_devices, batch=16, steps_cfg="smoke", mesh_model=1):
    cfg = load_config(steps_cfg)
    cfg.train.global_batch_size = batch
    cfg.mesh.model = mesh_model
    cfg.mesh.data = -1  # consume the remaining devices
    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    state = init_state(model, cfg.optim, sched, jax.random.PRNGKey(0),
                       jnp.zeros((1, 32, 32, 3)))
    mesh = create_mesh(cfg.mesh, devices=jax.devices()[:n_devices])
    state = jax.device_put(state, replicated(mesh))
    step_fn = shard_step(
        make_train_step(model, cfg.optim, sched, cfg.data.num_classes,
                        augment_fn=None, base_rng=jax.random.PRNGKey(1)),
        mesh)
    return cfg, mesh, state, step_fn


def test_single_vs_8device_equivalence():
    """The same global batch must produce (numerically) the same update on a
    1-device and an 8-device mesh — the property that makes one SPMD code
    path subsume the reference's serial/PS/Horovod modes."""
    imgs = np.random.default_rng(0).normal(size=(16, 32, 32, 3)).astype(np.float32)
    labels = np.random.default_rng(1).integers(0, 10, 16).astype(np.int32)
    results = []
    for n_dev in (1, 8):
        _, mesh, state, step_fn = _setup(n_dev)
        bs = batch_sharding(mesh)
        gi, gl = jax.device_put(imgs, bs), jax.device_put(labels, bs)
        for _ in range(3):
            state, metrics = step_fn(state, gi, gl)
        results.append((jax.device_get(state.params),
                        float(metrics["loss"])))
    p1, l1 = results[0]
    p8, l8 = results[1]
    assert l1 == pytest.approx(l8, rel=1e-4)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat8 = jax.tree_util.tree_leaves(p8)
    for a, b in zip(flat1, flat8):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


def test_2d_mesh_data_model_equivalence():
    """A (data=4, model=2) mesh must run the identical SPMD program —
    state replicates over the unused 'model' axis and the update matches
    the 8x1 mesh bit-for-comparable-bits. This is the 'mesh abstraction
    does not preclude tensor/sequence axes' guarantee (SURVEY.md §5 long-
    context note): adding a real model/sequence sharding is a new
    PartitionSpec, not a redesign."""
    imgs = np.random.default_rng(0).normal(
        size=(16, 32, 32, 3)).astype(np.float32)
    labels = np.random.default_rng(1).integers(0, 10, 16).astype(np.int32)
    results = []
    for model_axis in (1, 2):
        _, mesh, state, step_fn = _setup(8, mesh_model=model_axis)
        assert dict(mesh.shape) == {"data": 8 // model_axis,
                                    "model": model_axis}
        bs = batch_sharding(mesh)
        gi, gl = jax.device_put(imgs, bs), jax.device_put(labels, bs)
        for _ in range(2):
            state, metrics = step_fn(state, gi, gl)
        results.append((jax.device_get(state.params),
                        float(metrics["loss"])))
    (p_1d, l_1d), (p_2d, l_2d) = results
    assert l_1d == pytest.approx(l_2d, rel=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(p_1d),
                    jax.tree_util.tree_leaves(p_2d)):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


def test_loss_decreases_memorization():
    cfg, mesh, state, step_fn = _setup(8, batch=32)
    imgs, labels = synthetic_data(32, 32, 10, seed=0)
    bs = batch_sharding(mesh)
    gi = jax.device_put(imgs.astype(np.float32) / 255.0, bs)
    gl = jax.device_put(labels, bs)
    first = None
    for i in range(30):
        state, m = step_fn(state, gi, gl)
        if i == 0:
            first = float(m["loss"])
    last = float(m["loss"])
    assert last < first * 0.7, (first, last)


def test_step_counter_and_lr_in_metrics():
    cfg, mesh, state, step_fn = _setup(8)
    imgs, labels = synthetic_data(16, 32, 10)
    bs = batch_sharding(mesh)
    gi = jax.device_put(imgs.astype(np.float32), bs)
    gl = jax.device_put(labels, bs)
    state, m = step_fn(state, gi, gl)
    assert int(state.step) == 1
    assert float(m["learning_rate"]) == pytest.approx(cfg.optim.base_lr)


def test_l2_penalty_bn_exclusion():
    params = {"conv": {"kernel": jnp.ones((3, 3, 2, 2))},
              "bn": {"scale": jnp.ones((4,)), "bias": jnp.ones((4,))}}
    with_bn = float(l2_weight_penalty(params, include_bn=True))
    without = float(l2_weight_penalty(params, include_bn=False))
    assert with_bn == pytest.approx((36 + 8) / 2)
    assert without == pytest.approx(36 / 2)


def test_weight_decay_changes_loss():
    """Reference adds wd·Σl2(w) to the loss (resnet_model.py:85-86)."""
    cfg = load_config("smoke")
    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    imgs, labels = synthetic_data(8, 32, 10)
    imgs_f = jnp.asarray(imgs, jnp.float32)
    labels = jnp.asarray(labels)
    losses = {}
    for wd in (0.0, 0.01):
        cfg.optim.weight_decay = wd
        state = init_state(model, cfg.optim, sched, jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)))
        step_fn = make_train_step(model, cfg.optim, sched, 10,
                                  augment_fn=None)
        _, m = jax.jit(step_fn)(state, imgs_f, labels)
        losses[wd] = float(m["loss"])
    assert losses[0.01] > losses[0.0]


def test_train_loop_end_to_end(tmp_path):
    """Full loop: synthetic data, checkpoints written, resume continues —
    and every observability artifact of the run exists: metrics.jsonl with
    the step-time breakdown, events.jsonl spans, manifest.json, and a live
    /metrics + /healthz scrape while training (tpu_resnet/obs)."""
    import json
    import os
    import threading
    import time
    import urllib.request

    from tpu_resnet.obs.server import read_telemetry_port, scrape
    from tpu_resnet.obs.spans import load_spans
    from tpu_resnet.train import latest_step_in, train

    cfg = load_config("smoke")
    cfg.train.train_dir = str(tmp_path / "run")
    cfg.train.train_steps = 10
    cfg.train.checkpoint_every = 5
    cfg.train.log_every = 5
    cfg.train.image_summary_every = 5  # input-image channel (cifar_input.py:118)
    cfg.train.global_batch_size = 16
    cfg.train.telemetry_port = 0  # ephemeral; discovered via telemetry.json
    cfg.data.train_examples  # synthetic

    # Scrape the telemetry server WHILE training runs (it closes with the
    # loop): poll for telemetry.json, then take one /metrics + /healthz.
    scraped = {}

    def _scrape_live():
        deadline = time.time() + 120
        while time.time() < deadline:
            port = read_telemetry_port(cfg.train.train_dir)
            if port is not None:
                try:
                    scraped.update(scrape(f"127.0.0.1:{port}", timeout=5))
                    return
                except (OSError, ValueError):
                    pass
            time.sleep(0.02)

    scraper = threading.Thread(target=_scrape_live, daemon=True)
    scraper.start()
    state = train(cfg)
    scraper.join(timeout=10)
    assert int(jax.device_get(state.step)) == 10
    assert latest_step_in(cfg.train.train_dir) == 10
    assert os.path.exists(os.path.join(cfg.train.train_dir, "images",
                                       "input_images_step5.png"))
    assert os.path.exists(os.path.join(cfg.train.train_dir, "images",
                                       "input_images_step10.png"))

    # Live scrape: Prometheus text parsed, heartbeat fresh.
    assert scraped, "telemetry server was never scraped during training"
    assert "tpu_resnet_step" in scraped["metrics"]
    assert "tpu_resnet_images_per_sec" in scraped["metrics"]
    assert scraped["health"]["ok"] is True
    assert scraped["health"]["heartbeat_age_sec"] >= 0.0

    # Run manifest: resolved config + topology, written once at startup.
    with open(os.path.join(cfg.train.train_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["config"]["train"]["train_steps"] == 10
    assert manifest["devices"]["count"] >= 1
    assert manifest["processes"]["count"] == 1

    # metrics.jsonl carries the step-time breakdown on logged intervals.
    with open(os.path.join(cfg.train.train_dir, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    assert any("data_wait_frac" in r and "compile_seconds" in r
               for r in records)

    # Resume: raising train_steps continues from the checkpoint.
    cfg.train.train_steps = 14
    state = train(cfg)
    assert int(jax.device_get(state.step)) == 14

    # events.jsonl timeline: both runs' spans, including the restore.
    spans = load_spans(os.path.join(cfg.train.train_dir, "events.jsonl"))
    kinds = {s["span"] for s in spans}
    assert {"run", "compile", "checkpoint_save",
            "checkpoint_restore"} <= kinds
    run_spans = [s for s in spans if s["span"] == "run"]
    assert [s["stop_step"] for s in run_spans] == [10, 14]
    assert all(s["end"] >= s["start"] for s in spans)
