"""Check engine 5 (analysis/collectives.py + obs/comms.py): the HLO
collective parser (both replica-group spellings, the CPU reduce-scatter
re-derivation), the ring cost model, the golden workflow
(update/drift/missing/prune), the named semantic rules, and the
acceptance drills — the checked-in golden's zero1 twin bytes-ratio, the
collective-free serve bucket, and the no-wsc mutant that must fail
loudly."""

import json
import os
import subprocess
import sys

import pytest

from tpu_resnet.analysis import collectives, configmatrix, memorybudget
from tpu_resnet.analysis.configmatrix import MATRIX
from tpu_resnet.obs import comms

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BY_NAME = {e.name: e for e in MATRIX}
RN8 = BY_NAME["cifar10_rn8_f32"]
MESH8 = BY_NAME["cifar10_rn8_f32_mesh8"]
ZERO1 = BY_NAME["cifar10_rn8_f32_mesh8_zero1"]
ZERO1_MESH1 = BY_NAME["cifar10_rn8_f32_zero1_mesh1"]
MESH4X2 = BY_NAME["cifar10_rn8_f32_mesh4x2"]
SERVE = next(e for e in MATRIX if e.builder == "serve")


def _summary(**over):
    """A minimal clean comms summary; override per test."""
    base = {"mesh": "1x1", "collective_count": 0, "ops": {},
            "structure": {}, "bytes_by_axis": {},
            "wire_bytes_per_device": 0, "all_gather_bytes": 0,
            "reduce_scatter_bytes": 0, "plain_all_reduce_bytes": 0,
            "partition": "replicated", "params_argument_bytes": 312424}
    base.update(over)
    return base


# ------------------------------------------------------------ HLO parser

# Handcrafted post-SPMD HLO exercising every parser path at once: an
# all-reduce whose single consumer keeps 1/8 of the payload (the CPU
# reduce-scatter decomposition), an iota-form all-gather, a tuple
# all-reduce over the implicit full mesh, and a collective-permute.
HLO = """\
HloModule test

%sum (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add = f32[] add(%a, %b)
}

ENTRY %main (p0: f32[64]) -> f32[8] {
  %p0 = f32[64]{0} parameter(0)
  %ar.1 = f32[64]{0} all-reduce(%p0), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%sum
  %ds.1 = f32[8]{0} dynamic-slice(%ar.1, %c0), dynamic_slice_sizes={8}
  %ag.1 = f32[64]{0} all-gather(%ds.1), replica_groups=[1,8]<=[8], dimensions={0}
  %ar.2 = (f32[16]{0}, f32[16]{0}) all-reduce(%ag.1, %ag.1), replica_groups={}, to_apply=%sum
  ROOT %cp.1 = f32[8]{0} collective-permute(%ds.1), source_target_pairs={{0,1},{1,0}}
}
"""


def test_iota_groups_expansion():
    # the [2,4]<=[4,2]T(1,0) spelling of a 4x2 mesh's data-axis groups
    assert comms._iota_groups(2, 4, [4, 2], [1, 0]) == \
        [(0, 2, 4, 6), (1, 3, 5, 7)]
    assert comms._iota_groups(2, 4, [8], None) == \
        [(0, 1, 2, 3), (4, 5, 6, 7)]


def test_parse_groups_every_spelling():
    assert comms._parse_groups("replica_groups={{0,2},{1,3}}", 4) == \
        [(0, 2), (1, 3)]
    assert comms._parse_groups("replica_groups=[2,4]<=[4,2]T(1,0)", 8) \
        == [(0, 2, 4, 6), (1, 3, 5, 7)]
    # empty groups = one group of every device
    assert comms._parse_groups("replica_groups={}", 4) == [(0, 1, 2, 3)]
    assert comms._parse_groups("source_target_pairs={{0,1},{1,0}}", 4) \
        == [(0, 1), (1, 0)]
    # no annotation at all: same full-mesh default
    assert comms._parse_groups("channel_id=1", 2) == [(0, 1)]


def test_classify_groups_buckets():
    # 4x2 mesh, row-major ("data","model") device order
    assert comms.classify_groups([(0, 2, 4, 6), (1, 3, 5, 7)], 4, 2) \
        == "data"
    assert comms.classify_groups([(0, 1), (2, 3), (4, 5), (6, 7)], 4, 2) \
        == "model"
    assert comms.classify_groups([tuple(range(8))], 4, 2) == "all"
    # both coordinates vary without covering the mesh: the violation
    assert comms.classify_groups([(0, 3)], 4, 2) == "mixed"
    assert comms.classify_groups([(0,)], 4, 2) == "self"
    # 1-D mesh: the full mesh is the data axis, never "all"
    assert comms.classify_groups([tuple(range(8))], 8, 1) == "data"


def test_type_bytes_and_dtype():
    assert comms._type_bytes("f32[3,3,16,16]{3,2,1,0}") == 9216
    assert comms._type_bytes("(f32[16]{0}, u8[4]{0})") == 68
    assert comms._type_bytes("f32[]") == 4
    assert comms._type_dtype("bf16[8,8]{1,0}") == "bf16"


def test_ring_wire_bytes():
    assert comms._ring_wire_bytes("all-reduce", 800, 8) == 1400.0
    assert comms._ring_wire_bytes("all-gather", 800, 8) == 700.0
    assert comms._ring_wire_bytes("reduce-scatter", 800, 8) == 700.0
    assert comms._ring_wire_bytes("collective-permute", 800, 2) == 800.0
    assert comms._ring_wire_bytes("all-reduce", 800, 1) == 0.0


def test_extract_collectives_handcrafted_hlo():
    cols = {c.name: c for c in comms.extract_collectives(HLO, 8, 1)}
    assert set(cols) == {"ar.1", "ag.1", "ar.2", "cp.1"}
    # ar.1's only consumer keeps 32 <= ceil(256/8)+4 bytes: the CPU
    # decomposer's all-reduce is re-derived as the logical reduce-scatter
    assert cols["ar.1"].op == "reduce-scatter"
    assert cols["ar.1"].raw_op == "all-reduce"
    assert cols["ar.1"].payload_bytes == 256
    assert cols["ar.1"].wire_bytes == 224.0
    assert cols["ag.1"].op == "all-gather"
    assert cols["ag.1"].group_size == 8 and cols["ag.1"].bucket == "data"
    # tuple all-reduce (combined small reductions) stays plain
    assert cols["ar.2"].op == "all-reduce"
    assert cols["ar.2"].payload_bytes == 128
    assert cols["cp.1"].op == "collective-permute"
    assert cols["cp.1"].wire_bytes == 32.0
    assert cols["ar.1"].signature() == "reduce-scatter|f32:256b|data|g8"


def test_extract_literal_reduce_scatter_payload_from_operand():
    """On TPU the literal op appears: the logical payload is the full
    OPERAND, not the sharded result."""
    hlo = ("ENTRY %main (p0: f32[64]) -> f32[8] {\n"
           "  %p0 = f32[64]{0} parameter(0)\n"
           "  ROOT %rs.1 = f32[8]{0} reduce-scatter(f32[64]{0} %p0), "
           "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, "
           "to_apply=%sum\n}\n")
    (c,) = comms.extract_collectives(hlo, 8, 1)
    assert c.op == c.raw_op == "reduce-scatter"
    assert c.payload_bytes == 256 and c.wire_bytes == 224.0


def test_summarize_collectives_budget():
    s = comms.summarize_collectives(HLO, 8, 1)
    assert s["mesh"] == "8x1" and s["collective_count"] == 4
    assert s["ops"] == {"all-gather": 1, "all-reduce": 1,
                        "collective-permute": 1, "reduce-scatter": 1}
    assert s["reduce_scatter_bytes"] == 256
    assert s["all_gather_bytes"] == 256
    assert s["plain_all_reduce_bytes"] == 128
    assert s["wire_bytes_per_device"] == 704
    assert s["bytes_by_axis"] == {"data": 704}
    assert sum(s["structure"].values()) == 4


def test_ici_table_and_override(monkeypatch):
    monkeypatch.delenv("TPU_RESNET_ICI_BYTES", raising=False)
    assert comms.ici_bytes_per_chip("TPU v5 lite") == 1600 * 1e9 / 8
    assert comms.ici_bytes_per_chip("TPU v5p chip") == 4800 * 1e9 / 8
    assert comms.ici_bytes_per_chip("cpu") is None
    assert comms.ici_bytes_per_chip("") is None
    monkeypatch.setenv("TPU_RESNET_ICI_BYTES", "1e9")
    assert comms.ici_bytes_per_chip("cpu") == 1e9
    monkeypatch.setenv("TPU_RESNET_ICI_BYTES", "not-a-number")
    assert comms.ici_bytes_per_chip("TPU v4") == 2400 * 1e9 / 8


def test_predicted_time_on_wire(monkeypatch):
    monkeypatch.setenv("TPU_RESNET_ICI_BYTES", "1000000.0")
    s = _summary(wire_bytes_per_device=500000)
    assert comms.predicted_time_on_wire(s, "cpu") == 0.5
    monkeypatch.delenv("TPU_RESNET_ICI_BYTES")
    assert comms.predicted_time_on_wire(s, "cpu") is None
    assert comms.predicted_time_on_wire(None, "TPU v4") is None


def test_comms_ledger_roundtrip(tmp_path):
    led = comms.CommsLedger()
    led.register("train/cifar10/rn8", _summary(), program="single-step")
    led.register("no-hlo-key", None)
    path = led.save(str(tmp_path))
    assert path and os.path.exists(path)
    back = comms.CommsLedger.load(str(tmp_path))
    assert back.keys() == ["no-hlo-key", "train/cifar10/rn8"]
    entry = back.get("train/cifar10/rn8")
    assert entry["comms_source"] == "compiled_hlo"
    assert entry["program"] == "single-step"
    assert back.get("no-hlo-key")["comms_source"] == "none"


# -------------------------------------------------------- golden compare

def test_compare_structure_exact_and_bytes_banded():
    want = _summary(ops={"all-reduce": 2},
                    structure={"all-reduce|f32:256b|data|g8": 2},
                    bytes_by_axis={"data": 1_000_000},
                    wire_bytes_per_device=1_000_000,
                    plain_all_reduce_bytes=500_000)
    assert collectives._compare("e", want, dict(want), 0.10) == []
    # inside the band / absolute slack: clean
    near = dict(want, wire_bytes_per_device=1_050_000,
                reduce_scatter_bytes=4000)
    assert collectives._compare("e", want, near, 0.10) == []
    # structure compares EXACTLY — one recount is a drift
    moved = dict(want, structure={"all-reduce|f32:256b|data|g8": 1,
                                  "all-gather|f32:256b|data|g8": 1})
    findings = collectives._compare("e", want, moved, 0.10)
    assert any(f.rule == "golden-collectives-drift"
               and "structure" in f.message and "added" in f.message
               for f in findings)
    # byte totals band: a doubled wire budget is a drift with the hint
    doubled = dict(want, wire_bytes_per_device=2_000_000)
    findings = collectives._compare("e", want, doubled, 0.10)
    assert len(findings) == 1
    assert "--update-golden" in findings[0].message
    # traffic moving BETWEEN axes is its own story
    shifted = dict(want, bytes_by_axis={"model": 1_000_000})
    findings = collectives._compare("e", want, shifted, 0.10)
    assert any("mesh axis" in f.message for f in findings)


# ---------------------------------------------------------- named rules

def test_rule_collective_free_serve():
    assert collectives._rule_collective_free_serve(SERVE, _summary()) == []
    findings = collectives._rule_collective_free_serve(
        SERVE, _summary(collective_count=2, ops={"all-reduce": 2}))
    assert [f.rule for f in findings] == ["collective-free-serve"]
    assert "fleet-wide hang" in findings[0].message
    # train rows are exempt whatever they contain
    assert collectives._rule_collective_free_serve(
        MESH8, _summary(collective_count=2)) == []


def test_rule_stray_gather():
    params = 312424
    ok = _summary(params_argument_bytes=params, all_gather_bytes=1000)
    assert collectives._rule_stray_gather(MESH8, ok) == []
    bad = _summary(params_argument_bytes=params,
                   all_gather_bytes=int(0.5 * params))
    findings = collectives._rule_stray_gather(MESH8, bad)
    assert [f.rule for f in findings] == ["stray-gather"]
    assert "ZeRO-bloat" in findings[0].message
    # zero1 rows legitimately gather the param footprint; serve rows
    # are owned by collective-free-serve
    assert collectives._rule_stray_gather(ZERO1, bad) == []
    assert collectives._rule_stray_gather(SERVE, bad) == []


def test_rule_axis_confinement():
    clean = _summary(bytes_by_axis={"data": 9999999, "model": 5000})
    assert collectives._rule_axis_confinement(MESH4X2, clean) == []
    bad = _summary(bytes_by_axis={"data": 10, "mixed": 8192})
    findings = collectives._rule_axis_confinement(MESH4X2, bad)
    assert [f.rule for f in findings] == ["axis-confinement"]
    # 1-D meshes have no second axis to violate
    assert collectives._rule_axis_confinement(MESH8, bad) == []


def test_rule_zero1_exchange():
    params = 312424
    good = _summary(partition="zero1", params_argument_bytes=params,
                    reduce_scatter_bytes=315372, all_gather_bytes=317184,
                    plain_all_reduce_bytes=40)
    twin = _summary(plain_all_reduce_bytes=315304)
    assert collectives._rule_zero1_exchange(ZERO1, good, twin) == []
    # missing exchange (the gradient all-reduce stayed plain): both
    # floors fire AND the twin ceiling catches the un-replaced traffic
    missing = _summary(partition="zero1", params_argument_bytes=params,
                       plain_all_reduce_bytes=315304)
    findings = collectives._rule_zero1_exchange(ZERO1, missing, twin)
    assert len(findings) == 3  # rs floor, ag floor, plain not replaced
    assert all(f.rule == "zero1-exchange" for f in findings)
    # ...the plain ceiling needs the twin; floors alone without it
    assert len(collectives._rule_zero1_exchange(ZERO1, missing, None)) == 2
    # plain all-reduce riding ALONGSIDE the exchange
    riding = dict(good, plain_all_reduce_bytes=315304)
    findings = collectives._rule_zero1_exchange(ZERO1, riding, twin)
    assert len(findings) == 1 and "REPLACE" in findings[0].message
    # zero1 on a 1-way data axis is the replicated identity: exempt
    assert collectives._rule_zero1_exchange(ZERO1_MESH1, missing,
                                            twin) == []


# ------------------------------------------------- verify flow (stubbed)

def test_verify_collectives_update_drift_missing_prune(tmp_path,
                                                       monkeypatch):
    """Engine flow with a stubbed compiler: update writes the golden
    (tolerance + jax version recorded, stale entries pruned), a verify
    round-trips clean, a mutated structure drifts, a missing entry is
    reported."""
    import jax

    monkeypatch.setattr(collectives, "entry_comms_summary",
                        lambda entry: _summary())
    golden_path = str(tmp_path / "golden_collectives.json")
    collectives.save_golden(
        {"format": 1, "entries": {"renamed_entry": _summary()}},
        golden_path)
    findings, stats = collectives.verify_collectives(
        entries=(RN8,), update_golden=True, golden_path=golden_path)
    assert findings == [] and stats["updated"] == [RN8.name]
    golden = collectives.load_golden(golden_path)
    assert set(golden["entries"]) == {RN8.name}
    assert golden["tolerance"] == collectives.DEFAULT_TOLERANCE
    assert golden["jax"] == jax.__version__

    findings, stats = collectives.verify_collectives(
        entries=(RN8,), golden_path=golden_path)
    assert findings == [] and stats["compared"] == 1

    monkeypatch.setattr(
        collectives, "entry_comms_summary",
        lambda entry: _summary(collective_count=1, ops={"all-gather": 1},
                               structure={"all-gather|f32:256b|data|g8": 1}))
    findings, _ = collectives.verify_collectives(entries=(RN8,),
                                                 golden_path=golden_path)
    assert findings and all(f.rule == "golden-collectives-drift"
                            for f in findings)

    findings, _ = collectives.verify_collectives(
        entries=(RN8,), golden_path=str(tmp_path / "empty.json"))
    assert any("no golden collectives summary" in f.message
               for f in findings)


def test_verify_collectives_rules_run_under_update(tmp_path, monkeypatch):
    """--update-golden can never bake a violation into the golden: the
    semantic rules run in update mode too."""
    monkeypatch.setattr(
        collectives, "entry_comms_summary",
        lambda entry: _summary(collective_count=1, ops={"all-reduce": 1}))
    findings, _ = collectives.verify_collectives(
        entries=(SERVE,), update_golden=True,
        golden_path=str(tmp_path / "g.json"))
    assert [f.rule for f in findings] == ["collective-free-serve"]


def test_verify_collectives_compile_failure_is_per_entry(tmp_path,
                                                         monkeypatch):
    def boom(entry):
        raise RuntimeError("lowering exploded")

    monkeypatch.setattr(collectives, "entry_comms_summary", boom)
    findings, stats = collectives.verify_collectives(
        entries=(RN8,), golden_path=str(tmp_path / "g.json"))
    assert stats["failed"] == 1
    assert [f.rule for f in findings] == ["collectives-budget"]


def test_verify_collectives_zero1_sees_twin(tmp_path, monkeypatch):
    """The two-pass flow: the zero1 row's plain-ceiling gate reads the
    replicated twin's summary compiled in the same run."""
    def fake(entry):
        if entry.partition == "zero1":
            return _summary(partition="zero1",
                            reduce_scatter_bytes=315372,
                            all_gather_bytes=317184,
                            plain_all_reduce_bytes=200_000)  # riding
        return _summary(plain_all_reduce_bytes=315304)

    monkeypatch.setattr(collectives, "entry_comms_summary", fake)
    findings, _ = collectives.verify_collectives(
        entries=(MESH8, ZERO1), update_golden=True,
        golden_path=str(tmp_path / "g.json"))
    assert any(f.rule == "zero1-exchange" and "REPLACE" in f.message
               for f in findings)


# ------------------------------------- checked-in golden acceptance gates

def _checked_in():
    golden = collectives.load_golden()
    assert golden["entries"], "analysis/golden_collectives.json missing"
    return golden["entries"]


def test_checked_in_golden_mirrors_matrix():
    entries = _checked_in()
    live = {e.name for e in MATRIX
            if e.expect_error is None and e.builder != "ctor-bn-axis"}
    assert set(entries) == live
    golden = collectives.load_golden()
    assert golden["format"] == collectives.GOLDEN_FORMAT
    assert "tolerance" in golden and "jax" in golden


def test_checked_in_golden_zero1_twin_bytes_ratio():
    """THE acceptance artifact of the zero1 comms story, gated on the
    committed goldens (no compile): the scattered/gathered bytes each
    cover >= 75% of the param footprint and the plain all-reduce bytes
    collapsed below 50% of the replicated twin's."""
    entries = _checked_in()
    gated = 0
    for e in MATRIX:
        if e.partition != "zero1" or e.data_axis <= 1 \
                or e.name not in entries:
            continue
        z = entries[e.name]
        twin = entries[e.name.replace("_zero1", "")]
        params = z["params_argument_bytes"]
        assert params > 0, e.name
        assert z["reduce_scatter_bytes"] >= \
            collectives.ZERO1_MIN_EXCHANGE_FRACTION * params, e.name
        assert z["all_gather_bytes"] >= \
            collectives.ZERO1_MIN_EXCHANGE_FRACTION * params, e.name
        assert z["plain_all_reduce_bytes"] < \
            collectives.ZERO1_MAX_PLAIN_FRACTION * \
            twin["plain_all_reduce_bytes"], e.name
        gated += 1
    assert gated >= 2  # mesh8 + mesh4x2 zero1 rows at minimum


def test_checked_in_golden_serve_rows_collective_free():
    entries = _checked_in()
    serve = [e for e in MATRIX if e.builder == "serve"
             and e.name in entries]
    assert serve and any(e.name.endswith("_q8") for e in serve)
    for e in serve:
        assert entries[e.name]["collective_count"] == 0, e.name
        assert entries[e.name]["wire_bytes_per_device"] == 0, e.name


def test_checked_in_golden_single_device_rows_are_silent():
    """1x1 rows (RN8 and friends) put nothing on the wire — a
    collective appearing there would be a partitioner leak."""
    entries = _checked_in()
    for e in MATRIX:
        if e.name in entries and e.data_axis * e.model_axis == 1 \
                and e.partition != "zero1":
            assert entries[e.name]["wire_bytes_per_device"] == 0, e.name


# -------------------------------------------- real-compile tier-1 drills

def test_golden_collectives_subset_matches_checked_in():
    """Fast tier-1 gate on the REAL golden: the cheapest matrix entry
    compiles to the committed summary (the full-matrix verify is the
    slow-tier twin; `tpu-resnet check` runs it for operators). Shares
    the per-process compile cache with test_memory's subset gate."""
    findings, stats = collectives.verify_collectives(entries=(RN8,))
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stats["compiled"] == stats["compared"] == 1


def test_update_golden_reproduces_checked_in_entries(tmp_path):
    """The satellite-6 byte-stability contract at entry granularity:
    regenerating RN8's goldens through all three engines reproduces the
    committed entries EXACTLY — `check --update-golden` must never churn
    entries whose programs did not change."""
    cases = (
        (configmatrix.verify_matrix, configmatrix, "golden_jaxprs.json"),
        (memorybudget.verify_memory, memorybudget, "golden_memory.json"),
        (collectives.verify_collectives, collectives,
         "golden_collectives.json"),
    )
    for verify, mod, fname in cases:
        path = str(tmp_path / fname)
        findings, _ = verify(entries=(RN8,), update_golden=True,
                             golden_path=path)
        assert findings == [], fname
        with open(path) as fh:
            fresh = json.load(fh)["entries"][RN8.name]
        with open(os.path.join(os.path.dirname(mod.__file__),
                               fname)) as fh:
            committed = json.load(fh)["entries"][RN8.name]
        assert fresh == committed, fname


def test_no_wsc_mutation_caught():
    """Acceptance drill: compile the mesh8 zero1 entry's REAL program
    with the partitioner's sharding constraints deliberately dropped
    (identity wsc hooks) — the zero1-exchange gate and the checked-in
    golden must both catch it loudly."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from tpu_resnet.data import augment as aug_lib
    from tpu_resnet.models import build_model
    from tpu_resnet.parallel.partition import StatePartitioner
    from tpu_resnet.train import schedule as sched_lib
    from tpu_resnet.train.state import init_state
    from tpu_resnet.train.step import make_train_step, shard_step

    class NoWsc(StatePartitioner):
        """zero1 mode whose constraints never reach the program — the
        regression the engine exists to catch."""

        def constrain_slots(self, tree):
            return tree

        def constrain_opt_state(self, opt_state):
            return opt_state

        def constrain_replicated(self, tree):
            return tree

    cfg = ZERO1.to_config()
    model = build_model(cfg)
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)
    state_sds = jax.eval_shape(
        lambda r: init_state(model, cfg.optim, schedule, r, sample),
        jax.random.PRNGKey(0))
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8, 1),
                ("data", "model"))
    partitioner = NoWsc(mesh, "zero1")
    augment_fn, _ = aug_lib.get_augment_fns(cfg.data.dataset)
    base = make_train_step(model, cfg.optim, schedule,
                           cfg.data.num_classes, augment_fn,
                           base_rng=jax.random.PRNGKey(0), mesh=mesh,
                           partitioner=partitioner)
    imgs = jax.ShapeDtypeStruct((ZERO1.batch, size, size, 3), jnp.uint8)
    labels = jax.ShapeDtypeStruct((ZERO1.batch,), jnp.int32)
    # replicated inputs: exactly what "constraints not reaching the
    # compiled program" produces end to end
    compiled = shard_step(base, mesh).lower(state_sds, imgs,
                                            labels).compile()
    text = comms.hlo_text_of(compiled)
    assert text is not None
    summary = comms.summarize_collectives(text, 8, 1)
    summary["partition"] = "zero1"
    golden = collectives.load_golden()["entries"]
    summary["params_argument_bytes"] = \
        golden[ZERO1.name]["params_argument_bytes"]
    twin = golden[ZERO1.name.replace("_zero1", "")]
    findings = collectives._rule_zero1_exchange(ZERO1, summary, twin)
    assert any(f.rule == "zero1-exchange" for f in findings), \
        "the dropped-wsc mutant must fail the exchange gate"
    drift = collectives._compare(ZERO1.name, golden[ZERO1.name], summary,
                                 collectives.DEFAULT_TOLERANCE)
    assert any(f.rule == "golden-collectives-drift"
               and "structure" in f.message for f in drift), \
        "\n".join(f.format() for f in drift)


@pytest.mark.slow
def test_golden_collectives_full_matrix_matches_checked_in():
    """The full verify `tpu-resnet check` runs: every traced matrix
    entry's collective summary matches its committed golden (shares the
    memory engine's compile cache when both slow tests run in one
    process)."""
    findings, stats = collectives.verify_collectives()
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.format() for f in errors)
    assert stats["compared"] == stats["compiled"] >= 25


# ------------------------------------------------------------ CLI contract

def test_cli_list_rules_names_engine5():
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_resnet", "check", "--list-rules"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout
    for rule in ("golden-collectives-drift", "stray-gather",
                 "axis-confinement", "collective-free-serve",
                 "zero1-exchange", "collectives-budget",
                 "sharding-scope"):
        assert rule in proc.stdout, rule


def test_cli_unknown_rule_exits_2():
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_resnet", "check", "--skip-matrix",
         "--rules", "no-such-rule"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=120)
    assert proc.returncode == 2
    assert "unknown rule" in proc.stdout


def test_cli_skip_collectives_preserves_baseline_entries(tmp_path):
    """A partial run (--skip-matrix implies the collectives engine did
    not run) must MERGE on --write-baseline: accepted engine-5 entries
    survive verbatim instead of being silently deleted."""
    fixtures = os.path.join(REPO, "tests", "fixtures", "analysis")
    bl = str(tmp_path / "bl.json")
    with open(bl, "w") as fh:
        json.dump([{"fingerprint": "c" * 16, "rule": "zero1-exchange",
                    "path": "<golden-collectives>/x", "message": "m"},
                   {"fingerprint": "d" * 16,
                    "rule": "golden-collectives-drift",
                    "path": "<golden-collectives>/y", "message": "m"}],
                  fh)
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_resnet", "check", "--skip-matrix",
         "--root", os.path.join(fixtures, "signal_bad"),
         "--baseline", bl, "--write-baseline"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout
    assert "preserved" in proc.stdout
    with open(bl) as fh:
        rules = {e["rule"] for e in json.load(fh)}
    assert {"zero1-exchange", "golden-collectives-drift",
            "signal-safety"} <= rules
