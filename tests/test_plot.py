"""Plot/CSV results-artifact tool (reference results/cifar10.jpeg +
ps1workers1.csv role, SURVEY.md §2.2 results artifacts)."""

import json
import os

from tpu_resnet.tools.plot_metrics import load_series, plot


def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn tail')  # live-writer torn line must be tolerated


def test_plot_and_csv(tmp_path):
    run = tmp_path / "run"
    _write_jsonl(str(run / "metrics.jsonl"), [
        {"step": s, "loss": 2.0 / (1 + s), "precision": min(1.0, s / 100),
         "steps_per_sec": 0.3, "images_per_sec_per_chip": 2.5,
         # step-time breakdown channel (tpu_resnet/obs/breakdown.py)
         "data_wait_frac": 0.1 + s / 1000, "compile_seconds": 3.2,
         "device_step_sec_sampled": 0.05}
        for s in (20, 40, 60, 80, 100)])
    _write_jsonl(str(run / "eval" / "metrics.jsonl"), [
        {"step": 50, "Precision": 0.4, "Best_Precision": 0.4,
         "eval_loss": 1.0},
        {"step": 100, "Precision": 0.9, "Best_Precision": 0.9,
         "eval_loss": 0.5}])

    out = plot(str(run), csv_out=str(run / "series.csv"))
    assert os.path.exists(out) and os.path.getsize(out) > 10_000
    csv_text = (run / "series.csv").read_text()
    assert csv_text.splitlines()[0].startswith("series,step")
    assert any(line.startswith("eval,100") for line in csv_text.splitlines())
    assert len(load_series(str(run / "metrics.jsonl"))) == 5  # torn line ok


def test_plot_without_breakdown_keys(tmp_path):
    """Runs recorded before the obs layer (no data_wait_frac /
    compile_seconds) must still render."""
    run = tmp_path / "run"
    _write_jsonl(str(run / "metrics.jsonl"),
                 [{"step": 1, "loss": 1.0, "precision": 0.1},
                  {"step": 2, "loss": 0.5, "precision": 0.2}])
    out = plot(str(run))
    assert os.path.exists(out) and os.path.getsize(out) > 10_000
