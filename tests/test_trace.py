"""Chrome-trace timeline exporter (tpu_resnet/obs/trace.py): schema
validity, lane/counter construction, run_id correlation, deterministic
re-export — on synthetic artifacts and on a real tiny train run."""

import json
import os

import pytest

from tpu_resnet.obs.trace import (
    SERVE_EVENTS_FILE,
    build_trace,
    export_trace,
    main as trace_main,
    validate_trace,
)


def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


@pytest.fixture
def run_dir(tmp_path):
    """A synthetic train_dir with every artifact class the exporter
    merges: train spans, metrics with breakdown + engine counters, eval
    sidecar spans (same run_id), serve spans, manifest + run_id."""
    d = str(tmp_path / "run")
    rid = "deadbeef1234"
    t0 = 1_700_000_000.0
    _write_jsonl(os.path.join(d, "events.jsonl"), [
        {"span": "compile", "start": t0, "end": t0 + 3.5, "pid": 111,
         "run_id": rid, "step": 0},
        {"span": "checkpoint_save", "start": t0 + 10, "end": t0 + 10.4,
         "pid": 111, "run_id": rid, "step": 50, "async": True},
        {"span": "preempt_stop", "start": t0 + 30, "end": t0 + 30,
         "pid": 111, "run_id": rid, "step": 90},
        {"span": "run", "start": t0, "end": t0 + 31, "pid": 111,
         "run_id": rid, "start_step": 0, "stop_step": 90},
    ])
    _write_jsonl(os.path.join(d, "metrics.jsonl"), [
        {"step": 20, "wall": t0 + 8, "loss": 2.1, "steps_per_sec": 4.0,
         "data_wait_sec": 0.2, "data_wait_frac": 0.04,
         "dispatch_sec": 0.5, "mfu": 0.31,
         "model_flops_per_sec": 1.2e12, "data_ring_occupancy": 3.0,
         "data_decode_images_per_sec": 800.0},
        {"step": 40, "wall": t0 + 13, "loss": 1.9, "steps_per_sec": 4.1,
         "data_wait_sec": 0.1, "data_wait_frac": 0.02,
         "dispatch_sec": 0.5, "mfu": 0.32,
         "model_flops_per_sec": 1.25e12, "data_ring_occupancy": 4.0,
         "data_decode_images_per_sec": 810.0},
    ])
    _write_jsonl(os.path.join(d, "eval", "events.jsonl"), [
        {"span": "eval_pass", "start": t0 + 11, "end": t0 + 14,
         "pid": 222, "run_id": rid, "step": 50, "precision": 0.7},
    ])
    _write_jsonl(os.path.join(d, SERVE_EVENTS_FILE), [
        {"span": "serve_warmup", "start": t0 + 20, "end": t0 + 22,
         "pid": 333, "run_id": rid, "model_step": 50},
        {"span": "serve_reload", "start": t0 + 25, "end": t0 + 25.2,
         "pid": 333, "run_id": rid, "model_step": 90},
    ])
    with open(os.path.join(d, "run_id.json"), "w") as f:
        json.dump({"run_id": rid}, f)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({"schema": 2, "run_id": rid}, f)
    return d


def test_trace_schema_and_lanes(run_dir):
    trace = build_trace(run_dir)
    assert validate_trace(trace) == []
    meta = trace["metadata"]
    assert meta["run_id"] == "deadbeef1234"
    # every source reported the SAME run_id — the correlated-session claim
    assert meta["source_run_ids"] == {
        "train": ["deadbeef1234"], "eval": ["deadbeef1234"],
        "serve": ["deadbeef1234"]}

    events = trace["traceEvents"]
    pids = {e["pid"] for e in events}
    assert {111, 222, 333} <= pids  # three process lanes
    names = {e["name"] for e in events}
    assert {"run", "compile", "eval_pass", "serve_warmup",
            "serve_reload"} <= names
    # process lanes labeled with the run_id
    proc_names = {e["args"]["name"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert any("trainer run=deadbeef1234" == n for n in proc_names)
    assert any(n.startswith("eval-sidecar") for n in proc_names)
    assert any(n.startswith("serve") for n in proc_names)

    # counters: breakdown + data-engine ring series, values preserved
    counters = [e for e in events if e["ph"] == "C"]
    by_name = {}
    for c in counters:
        by_name.setdefault(c["name"], []).append(c["args"]["value"])
    assert by_name["mfu"] == [0.31, 0.32]
    assert by_name["data_ring_occupancy"] == [3.0, 4.0]
    assert by_name["steps_per_sec"] == [4.0, 4.1]

    # interval slice carries the breakdown args
    (interval,) = [e for e in events
                   if e["name"].startswith("train_interval")]
    assert interval["ph"] == "X"
    assert interval["dur"] == pytest.approx(5e6)
    assert interval["args"]["data_wait_frac"] == 0.02

    # zero-duration spans render as instants, ts are sorted + non-negative
    (instant,) = [e for e in events if e["name"] == "preempt_stop"]
    assert instant["ph"] == "i"
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts) and ts[0] >= 0


def test_trace_export_deterministic_and_cli(run_dir, tmp_path, capsys):
    out1 = str(tmp_path / "a.json")
    out2 = str(tmp_path / "b.json")
    path1, trace1 = export_trace(run_dir, out=out1)
    assert path1 == out1
    assert validate_trace(trace1) == []
    export_trace(run_dir, out=out2)
    with open(out1, "rb") as a, open(out2, "rb") as b:
        assert a.read() == b.read()  # stable under re-export

    # default output path + CLI wrapper
    assert trace_main(["--dir", run_dir]) == 0
    assert "run_id=deadbeef1234" in capsys.readouterr().out
    with open(os.path.join(run_dir, "trace.json")) as f:
        assert validate_trace(json.load(f)) == []


def test_trace_export_tolerates_partial_dirs(tmp_path):
    # nothing at all → loud error, not an empty trace
    with pytest.raises(FileNotFoundError):
        build_trace(str(tmp_path))
    assert trace_main(["--dir", str(tmp_path)]) == 1
    # metrics-only (no spans, no manifest): still a valid trace
    _write_jsonl(str(tmp_path / "metrics.jsonl"),
                 [{"step": 5, "wall": 100.0, "steps_per_sec": 2.0},
                  {"step": 10, "wall": 105.0, "steps_per_sec": 2.1,
                   "data_wait_sec": 0.1}])
    trace = build_trace(str(tmp_path))
    assert validate_trace(trace) == []
    assert trace["metadata"]["run_id"] is None
    assert any(e["ph"] == "C" for e in trace["traceEvents"])


def test_validate_trace_catches_bad_traces():
    assert validate_trace([]) == ["trace is not a JSON object"]
    assert validate_trace({}) == ["traceEvents missing or not a list"]
    bad = {"traceEvents": [
        {"name": "a", "ph": "X", "pid": 1, "ts": 5.0, "dur": -1},
        {"name": "b", "ph": "??", "pid": 1, "ts": 1.0},
        {"ph": "C", "pid": 1, "ts": 2.0},
    ]}
    problems = "\n".join(validate_trace(bad))
    assert "dur >= 0" in problems
    assert "unknown phase" in problems
    assert "missing required key 'name'" in problems
    assert "must be sorted" in problems


def test_trace_export_on_real_train_run(tmp_path, monkeypatch):
    """Integration: a real tiny CPU train (telemetry artifacts written by
    the actual loop) exports a schema-valid trace whose run_id matches
    the manifest and whose counters carry the live mfu series."""
    from tpu_resnet.config import load_config
    from tpu_resnet.train import train

    # CPU has no entry in the peak-FLOPs table; the documented override
    # makes the mfu gauge genuinely nonzero (same trick doctor
    # --trace-probe uses).
    monkeypatch.setenv("BENCH_PEAK_FLOPS", "1e12")
    cfg = load_config("smoke")
    cfg.model.name = "mlp"
    cfg.data.device_resident = "off"
    cfg.data.transfer_stage = 1
    cfg.train.train_dir = str(tmp_path / "run")
    cfg.train.train_steps = 8
    cfg.train.checkpoint_every = 4
    cfg.train.log_every = 2
    cfg.train.summary_every = 2
    cfg.train.image_summary_every = 0
    cfg.train.steps_per_call = 2
    cfg.train.global_batch_size = 16
    train(cfg)

    # ... the eval sidecar evaluates the final checkpoint ...
    import copy

    from tpu_resnet.evaluation import evaluate

    eval_cfg = copy.deepcopy(cfg)
    eval_cfg.train.eval_once = True
    assert evaluate(eval_cfg) is not None

    # ... and a serve session (real checkpoint backend) warms and drains.
    from tpu_resnet.obs import read_run_id
    from tpu_resnet.obs.spans import SpanTracer
    from tpu_resnet.serve.server import PredictServer

    serve_cfg = copy.deepcopy(cfg)
    serve_cfg.serve.port = 0
    serve_cfg.serve.host = "127.0.0.1"
    serve_cfg.serve.max_batch = 2
    serve_cfg.serve.reload_interval_secs = 0
    spans = SpanTracer(cfg.train.train_dir, filename=SERVE_EVENTS_FILE,
                       run_id=read_run_id(cfg.train.train_dir))
    srv = PredictServer(serve_cfg, spans=spans).start()
    srv.drain(10.0)
    srv.close()
    spans.close()

    path, trace = export_trace(cfg.train.train_dir)
    assert validate_trace(trace) == []
    with open(os.path.join(cfg.train.train_dir, "manifest.json")) as f:
        manifest = json.load(f)
    rid = manifest["run_id"]
    assert rid
    assert trace["metadata"]["run_id"] == rid
    # one correlated session: all three lanes report the SAME run_id
    assert trace["metadata"]["source_run_ids"] == {
        "train": [rid], "eval": [rid], "serve": [rid]}
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"run", "compile", "checkpoint_save", "mfu_account",
            "eval_pass", "serve_warmup", "serve_drain"} <= names
    counter_names = {e["name"] for e in trace["traceEvents"]
                     if e["ph"] == "C"}
    assert {"steps_per_sec", "data_wait_frac", "mfu",
            "model_flops_per_sec"} <= counter_names
    # the registry file the accounting wrote is readable and non-empty
    from tpu_resnet.obs.mfu import FlopsRegistry
    reg = FlopsRegistry.load(cfg.train.train_dir)
    (key,) = reg.to_dict()["entries"].keys()
    assert key.startswith("train|synthetic_mlp_f32|mesh")
    assert reg.flops(key) and reg.flops(key) > 0


@pytest.mark.slow  # live train subprocess + mid-run scrape (~40s); the
# exporter/schema/run_id plumbing is covered in the default tier above
def test_doctor_trace_probe_contract():
    """doctor --trace-probe: the live mfu gauge and train_step_ms
    histogram go live mid-run, the SIGTERM preemption contract holds,
    and the exported trace schema-checks with the manifest's run_id."""
    from tpu_resnet.tools.doctor import _check_trace_probe

    out = _check_trace_probe()
    assert out["ok"], out
    assert out["mfu"] > 0
    assert out["step_ms_observations"] > 0
    assert out["trace_events"] > 0
    assert out["run_id"]


def test_h2d_transfer_lane(tmp_path):
    """h2d_transfer spans (the double-buffered staged transfers) render
    on their own named thread of the trainer lane, with the byte counters
    lifted from metrics.jsonl — the overlap-visibility contract of the
    MFU campaign's transfer leg."""
    d = str(tmp_path / "run")
    t0 = 1_700_000_000.0
    _write_jsonl(os.path.join(d, "events.jsonl"), [
        {"span": "run", "start": t0, "end": t0 + 20, "pid": 7,
         "run_id": "r", "start_step": 0, "stop_step": 10},
        {"span": "h2d_transfer", "start": t0 + 1.0, "end": t0 + 1.2,
         "pid": 7, "run_id": "r", "bytes": 147648, "steps": 3},
        {"span": "h2d_transfer", "start": t0 + 2.0, "end": t0 + 2.3,
         "pid": 7, "run_id": "r", "bytes": 147648, "steps": 3},
    ])
    _write_jsonl(os.path.join(d, "metrics.jsonl"), [
        {"step": 6, "wall": t0 + 3, "loss": 2.0, "steps_per_sec": 3.0,
         "data_wait_sec": 0.1, "data_wait_frac": 0.02,
         "dispatch_sec": 0.4, "h2d_bytes_per_sec": 1.1e6,
         "h2d_overlap_frac": 0.8},
    ])
    trace = build_trace(d)
    assert validate_trace(trace) == []
    ev = trace["traceEvents"]
    h2d = [e for e in ev if e["name"] == "h2d_transfer"]
    assert len(h2d) == 2
    assert {e["tid"] for e in h2d} == {4}          # the transfer lane
    assert all(e["args"]["bytes"] == 147648 for e in h2d)
    run = next(e for e in ev if e["name"] == "run")
    assert run["tid"] != h2d[0]["tid"]              # distinct threads
    names = {(e.get("tid"), e["args"]["name"]) for e in ev
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert (4, "h2d-transfer") in names
    counters = {e["name"] for e in ev if e["ph"] == "C"}
    assert {"h2d_bytes_per_sec", "h2d_overlap_frac"} <= counters


def test_request_lanes_and_fleet_lane(run_dir):
    """Tail-sampled route_request/serve_request spans sharing a trace id
    render as per-request lanes (slowest first, drop-counted in the
    metadata, never silently capped), the replica span's queue/infer/
    stall segments are synthesized inside it, and fleetmon's spans get
    their own process lane."""
    rid = "deadbeef1234"
    t0 = 1_700_000_000.0
    _write_jsonl(os.path.join(run_dir, "route_events.jsonl"), [
        {"span": "route_request", "start": t0 + 20, "end": t0 + 20.5,
         "pid": 444, "run_id": rid, "trace_id": "tr-slow",
         "duration_sec": 0.5, "lane": "interactive", "status": 200,
         "sampled": "slow", "replica": "r0", "latency_ms": 500.0,
         "legs": [{"replicas": ["r0"], "status": 200,
                   "answered": "r0", "ms": 499.0}]},
        {"span": "route_request", "start": t0 + 21, "end": t0 + 21.05,
         "pid": 444, "run_id": rid, "trace_id": "tr-fast",
         "duration_sec": 0.05, "lane": "interactive", "status": 200,
         "sampled": "sampled", "replica": "r1", "latency_ms": 50.0},
    ])
    _write_jsonl(os.path.join(run_dir, SERVE_EVENTS_FILE), [
        {"span": "serve_warmup", "start": t0 + 19, "end": t0 + 19.5,
         "pid": 333, "run_id": rid, "model_step": 50},
        {"span": "serve_request", "start": t0 + 20.05,
         "end": t0 + 20.45, "pid": 333, "run_id": rid,
         "trace_id": "tr-slow", "duration_sec": 0.4, "status": 200,
         "sampled": "slow", "replica": "r0", "latency_ms": 400.0,
         "queue_wait_ms": 100.0, "infer_ms": 250.0,
         "pad_fraction": 0.5, "batch_size": 4, "n": 1},
    ])
    _write_jsonl(os.path.join(run_dir, "fleet_events.jsonl"), [
        {"span": "fleet_start", "start": t0 + 18, "end": t0 + 18,
         "pid": 555, "run_id": rid, "slo_ms": 50.0},
        {"span": "fleet_burn_alert", "start": t0 + 22, "end": t0 + 22,
         "pid": 555, "run_id": rid, "burn_rate_fast": 300.0,
         "burn_rate_slow": 120.0, "fleet_p99_ms": 420.0},
    ])
    trace = build_trace(run_dir)
    assert validate_trace(trace) == []
    meta = trace["metadata"]
    assert meta["request_lanes"] == {"traces": 2, "rendered": 2,
                                     "dropped": 0}
    assert meta["source_run_ids"]["route"] == [rid]
    assert meta["source_run_ids"]["fleet"] == [rid]

    events = trace["traceEvents"]
    lanes = {e["args"]["name"]: e for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"
             and e["pid"] == 7000000}
    # slowest trace is lane 1, by max span duration
    assert lanes["req tr-slow"]["tid"] == 1
    assert lanes["req tr-fast"]["tid"] == 2
    proc_names = {e["args"]["name"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert "requests (tail-sampled)" in proc_names
    assert any(n.startswith("fleetmon") for n in proc_names)

    req = [e for e in events if e.get("cat") == "request"]
    by_name = {e["name"]: e for e in req if e["tid"] == 1}
    assert {"route_request", "serve_request", "queue_wait", "infer",
            "stall"} <= set(by_name)
    # segments partition the replica span: 100ms wait + 250ms infer +
    # 50ms unattributed stall, nested inside it on the same lane
    assert by_name["queue_wait"]["dur"] == pytest.approx(1e5, abs=1.0)
    assert by_name["infer"]["dur"] == pytest.approx(2.5e5, abs=1.0)
    assert by_name["stall"]["dur"] == pytest.approx(5e4, abs=1.0)
    assert by_name["serve_request"]["ts"] >= by_name["route_request"]["ts"]
    assert by_name["route_request"]["args"]["trace_id"] == "tr-slow"
    assert by_name["route_request"]["args"]["legs"][0]["answered"] == "r0"
    # the fleet lane carries the alert instant
    assert any(e["name"] == "fleet_burn_alert" for e in events)
    # deterministic re-export with request lanes present
    assert build_trace(run_dir) == trace
