"""Scenario conductor (tpu_resnet/scenario/): schema validation with
named errors (`scenario validate` rc-2 contract), argv/env construction
for child processes, template expansion, the catalog listing, and a
golden RESULT_JSON round-trip on a jax-free cmd-only scenario. The real
drills (scenarios/*.json) run in the slow tier — see
tests/test_scenario_drills.py."""

import importlib.util
import io
import json
import os
import shutil
import sys

import pytest

from tpu_resnet.resilience import exitcodes
from tpu_resnet.scenario import catalog, cli, spec
from tpu_resnet.scenario.conductor import (_build_argv, _child_env,
                                           conduct_file)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _base():
    """Smallest well-formed scenario; tests mutate copies of it."""
    return {
        "name": "t", "description": "d",
        "processes": {"p": {"kind": "cmd", "argv": ["true"]}},
        "steps": [{"do": "run", "proc": "p", "label": "go"}],
    }


def _errs(data):
    return [e["error"] for e in spec.validate_scenario(data)]


# ----------------------------------------------------- named validation

def test_root_must_be_object_and_required_fields_named():
    assert _errs([]) == ["not_an_object"]
    missing = spec.validate_scenario({})
    assert all(e["error"] == "missing_field" for e in missing)
    assert sorted(e["detail"].split("'")[1] for e in missing) == \
        ["description", "name", "processes", "steps"]


def test_unknown_and_mistyped_fields_are_named_with_paths():
    data = dict(_base(), extra=1)
    (err,) = spec.validate_scenario(data)
    assert (err["error"], err["where"]) == ("unknown_field", "$.extra")
    data = dict(_base(), name=3)
    (err,) = spec.validate_scenario(data)
    assert (err["error"], err["where"]) == ("bad_type", "$.name")


def test_empty_processes_and_steps_rejected():
    data = dict(_base(), processes={}, steps=[])
    assert sorted(_errs(data)) == ["empty", "empty"]


def test_unknown_process_kind_and_step_do():
    data = _base()
    data["processes"]["p"] = {"kind": "trainer"}
    assert "unknown_kind" in _errs(data)
    data = _base()
    data["steps"] = [{"do": "launch", "proc": "p"}]
    assert _errs(data) == ["unknown_step"]


def test_step_referencing_undeclared_process_is_named():
    data = _base()
    data["steps"] = [{"do": "run", "proc": "ghost"}]
    (err,) = spec.validate_scenario(data)
    assert (err["error"], err["where"]) == ("unknown_proc",
                                            "steps[0].proc")


def test_fault_keys_checked_against_faultinject_contract():
    data = _base()
    data["processes"]["p"]["faults"] = {"SIGKILL_STEP": 1}
    (err,) = spec.validate_scenario(data)
    assert err["error"] == "unknown_fault"
    assert "SIGKILL_STEP" in err["where"]
    # every documented fault key passes
    data["processes"]["p"]["faults"] = {k: 1 for k in spec.FAULT_KEYS}
    assert spec.validate_scenario(data) == []


def test_bad_expect_rc_values_are_named():
    for bad in ("crashed", True):
        data = _base()
        data["steps"][0]["expect_rc"] = bad
        assert "bad_expect_rc" in _errs(data), bad
    data = _base()
    data["steps"][0]["expect_rc"] = 1.5  # wrong type before rc check
    assert _errs(data) == ["bad_type"]
    data = _base()
    data["steps"][0]["expect_rc"] = ["preempt", 7, "nonzero"]
    assert spec.validate_scenario(data) == []


def test_duplicate_step_labels_rejected():
    data = _base()
    data["steps"] = [{"do": "sleep", "seconds": 0, "label": "x"},
                     {"do": "sleep", "seconds": 0, "label": "x"}]
    (err,) = spec.validate_scenario(data)
    assert (err["error"], err["where"]) == ("duplicate_label",
                                            "steps[1].label")


def test_unknown_assert_check_and_series_source():
    data = _base()
    data["assertions"] = [{"check": "nope"}]
    assert _errs(data) == ["unknown_check"]
    data = _base()
    data["series"] = [{"source": "nope", "id": "x"}]
    assert _errs(data) == ["unknown_source"]


def test_load_scenario_unreadable_and_toml_gate(tmp_path):
    _, errors = spec.load_scenario(str(tmp_path / "missing.json"))
    assert errors[0]["error"] == "unreadable"
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    _, errors = spec.load_scenario(str(bad))
    assert errors[0]["error"] == "unreadable"
    assert "JSON parse failed" in errors[0]["detail"]
    toml = tmp_path / "drill.toml"
    toml.write_text('name = "t"\n')
    _, errors = spec.load_scenario(str(toml))
    if importlib.util.find_spec("tomllib") is None:
        assert errors[0]["error"] == "toml_unsupported"
    else:
        assert all(e["error"] != "toml_unsupported" for e in errors)


# ------------------------------------------------------------------ CLI

def test_validate_cli_exits_usage_error_on_malformed_file(tmp_path,
                                                          capsys):
    path = tmp_path / "typo.json"
    path.write_text(json.dumps(dict(_base(), extra=1)))
    assert cli.main(["validate", str(path)]) == exitcodes.USAGE_ERROR
    out = capsys.readouterr().out
    assert "INVALID" in out
    assert "[unknown_field] $.extra" in out


def test_validate_cli_passes_every_checked_in_scenario(capsys):
    names = [s["name"] for s in catalog.list_scenarios()]
    assert len(names) >= 10
    assert cli.main(["validate"] + names) == 0
    assert capsys.readouterr().out.count(": ok") == len(names)


def test_run_cli_rejects_invalid_file_without_spawning(tmp_path,
                                                       capsys):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"name": "t"}))
    assert cli.main(["run", str(path), "--quiet"]) == \
        exitcodes.USAGE_ERROR
    result = json.loads(capsys.readouterr().out.split(
        "RESULT_JSON: ", 1)[1])
    assert result["phase"] == "validate"
    assert result["validation_errors"]


def test_list_covers_scenario_files_and_legacy_probes(capsys):
    assert cli.main(["list", "--paths"]) == 0
    out = capsys.readouterr().out
    for name in ("fault_drill", "serve_probe", "reshape_drill",
                 "corrupt_ckpt_while_polling",
                 "preempt_burst_under_fleet"):
        assert name in out, name
    for probe in catalog.LEGACY_PROBES:
        assert f"tools/doctor.py --{probe.replace('_', '-')}" in out


def test_catalog_parity_disk_validate_and_doctor_listing(capsys):
    """Catalog-parity gate across every surface: each scenarios/* file
    is cataloged with an existing path, each passes schema validation,
    and `doctor --list-probes` round-trips the SAME inventory as
    `scenario list` (both read catalog.list_scenarios — main.py routes
    the doctor flag there) including the legacy bespoke probes."""
    import subprocess

    entries = catalog.list_scenarios()
    assert entries
    on_disk = {f for f in os.listdir(catalog.scenarios_dir())
               if f.endswith((".json", ".toml"))}
    assert {os.path.basename(s["path"]) for s in entries} == on_disk
    for s in entries:
        assert os.path.exists(s["path"]), s["name"]
        assert s["description"] != "(unparseable scenario file)", s["name"]
    names = [s["name"] for s in entries]
    assert cli.main(["validate"] + names) == 0
    capsys.readouterr()
    assert cli.main(["list", "--paths"]) == 0
    listed = capsys.readouterr().out
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_resnet", "doctor", "--list-probes"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout
    for name in names:
        assert name in listed and name in proc.stdout, name
    for probe in catalog.LEGACY_PROBES:
        flag = probe.replace("_", "-")
        assert f"--{flag}" in listed and f"--{flag}" in proc.stdout, probe


# ------------------------------------------- child argv/env construction

def test_build_argv_cmd_is_verbatim_copy():
    proc = {"kind": "cmd", "argv": ["echo", "hi"]}
    argv = _build_argv(proc, REPO)
    assert argv == ["echo", "hi"]
    assert argv is not proc["argv"]


def test_build_argv_train_orders_preset_overrides_args():
    proc = {"kind": "train", "preset": "cifar_smoke",
            "overrides": {"train.total_steps": 40,
                          "checkpoint.enabled": True,
                          "resilience.drain_on_sigterm": False},
            "args": ["--workdir", "/tmp/w"]}
    assert _build_argv(proc, REPO) == [
        sys.executable, "-m", "tpu_resnet", "train",
        "--preset", "cifar_smoke",
        "train.total_steps=40", "checkpoint.enabled=true",
        "resilience.drain_on_sigterm=false",
        "--workdir", "/tmp/w"]


def test_build_argv_tool_kinds_resolve_scripts():
    assert _build_argv({"kind": "loadgen"}, REPO)[1] == \
        os.path.join(REPO, "tools", "loadgen.py")
    assert _build_argv({"kind": "supervise"}, REPO)[1] == \
        os.path.join(REPO, "tools", "supervise.py")
    assert _build_argv({"kind": "sweep"}, REPO)[1:] == \
        ["-m", "tpu_resnet.tools.sweep"]


def test_child_env_merges_faults_after_scrub(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_ID", "3")  # must be scrubbed
    env = _child_env({"kind": "cmd", "argv": [], "devices": 2,
                      "env": {"SCENARIO_FLAG": "1"},
                      "faults": {"SIGTERM_STEP": 20,
                                 "SERVE_DROP_REQ": 3}})
    assert "TPU_WORKER_ID" not in env
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "device_count=2" in env["XLA_FLAGS"]
    assert env["SCENARIO_FLAG"] == "1"
    # the fault schedule itself is TPU_-prefixed: it must survive the
    # scrub because it merges afterwards
    assert env["TPU_RESNET_FAULT_SIGTERM_STEP"] == "20"
    assert env["TPU_RESNET_FAULT_SERVE_DROP_REQ"] == "3"


def test_expand_templates_rewrites_only_known_placeholders():
    data = {"a": "{run}/ckpt", "b": ["{python}", "{root}/tools"],
            "c": {"space": '{"lr": [0.1]}', "n": 3}}
    out = spec.expand_templates(data, "/tmp/r", "/repo")
    assert out == {"a": "/tmp/r/ckpt",
                   "b": [sys.executable, "/repo/tools"],
                   "c": {"space": '{"lr": [0.1]}', "n": 3}}


def test_resolve_rc_maps_symbolic_names_through_exitcodes():
    assert spec.resolve_rc("done") == [exitcodes.DONE]
    assert spec.resolve_rc("preempt") == [exitcodes.PREEMPTED]
    assert spec.resolve_rc(["preempt", 7]) == [42, 7]
    assert spec.resolve_rc("any") is None
    assert spec.resolve_rc(["nonzero"]) == ["nonzero"]
    assert (exitcodes.PREEMPTED, exitcodes.NO_CAPACITY,
            exitcodes.DONE, exitcodes.DRAINED,
            exitcodes.USAGE_ERROR, exitcodes.HOSTENV_TIMEOUT,
            exitcodes.HOSTENV_SPAWN_FAILED) == (42, 3, 0, 0, 2, 124, 127)


# --------------------------------------------- conduct(): golden result

def _write_scenario(tmp_path, data):
    path = tmp_path / f"{data['name']}.json"
    path.write_text(json.dumps(data))
    return str(path)


def test_conduct_cmd_scenario_golden_result_round_trip(tmp_path):
    data = {
        "name": "golden", "description": "cmd-only golden drill",
        "tier": "fast",
        "processes": {
            "writer": {"kind": "cmd", "argv": [
                "{python}", "-c",
                "import sys; open(sys.argv[1], 'w').write('ok')",
                "{run}/artifact.txt"]},
            "failer": {"kind": "cmd", "argv": [
                "{python}", "-c", "raise SystemExit(7)"]},
        },
        "steps": [
            {"do": "run", "proc": "writer", "label": "write",
             "expect_rc": 0},
            {"do": "run", "proc": "failer", "label": "fail_ok",
             "expect_rc": [7, "preempt"]},
        ],
        "assertions": [{"check": "file_exists",
                        "path": "{run}/artifact.txt",
                        "label": "artifact"}],
    }
    assert spec.validate_scenario(data) == []
    path = _write_scenario(tmp_path, data)
    run_dir = str(tmp_path / "run")
    stream = io.StringIO()
    result = conduct_file(path, run_dir=run_dir, stream=stream)
    assert result["ok"] is True, result
    assert result["phase"] is None and result["error"] is None
    assert result["rcs"] == {"writer": 0, "failer": 7}
    assert [s["label"] for s in result["steps"]] == \
        ["write", "fail_ok", "artifact"]
    assert all(s["ok"] for s in result["steps"])
    assert result["perfwatch"] == {"ran": False}  # no series declared
    # golden round-trip: the RESULT_JSON line and the on-disk artifact
    # are byte-for-byte the same result the call returned
    line = [ln for ln in stream.getvalue().splitlines()
            if ln.startswith("RESULT_JSON: ")][-1]
    assert json.loads(line[len("RESULT_JSON: "):]) == result
    with open(os.path.join(run_dir, "scenario_result.json")) as f:
        assert json.load(f) == result


def test_conduct_failure_reports_contract_and_kills_survivors(tmp_path):
    data = {
        "name": "failing", "description": "rc mismatch kills survivors",
        "processes": {
            "sleeper": {"kind": "cmd", "argv": [
                "{python}", "-c", "import time; time.sleep(60)"]},
            "failer": {"kind": "cmd", "argv": [
                "{python}", "-c", "raise SystemExit(7)"]},
        },
        "steps": [
            {"do": "start", "proc": "sleeper", "label": "bg"},
            {"do": "run", "proc": "failer", "label": "boom",
             "phase": "blast", "expect_rc": 0},
        ],
    }
    path = _write_scenario(tmp_path, data)
    result = conduct_file(path, run_dir=str(tmp_path / "run"),
                          stream=None)
    assert result["ok"] is False
    assert result["phase"] == "blast"
    failed = result["steps"][-1]
    assert failed["label"] == "boom" and not failed["ok"]
    assert failed["observed"]["rc"] == 7
    assert failed["observed"]["expected_rc"] == 0
    # survivor kill: the background sleeper must not outlive the drill
    pid = result["steps"][0]["observed"]["pid"]
    with pytest.raises(OSError):
        os.kill(pid, 0)


# ------------------------------------------------- catalog + host rules

def test_catalog_lists_every_checked_in_drill_with_tier():
    entries = {s["name"]: s for s in catalog.list_scenarios()}
    for name in ("fault_drill", "serve_probe", "trace_probe",
                 "mem_probe", "partition_probe", "reshape_drill",
                 "sweep_probe", "corrupt_ckpt_while_polling",
                 "preempt_burst_under_fleet", "reshape_during_burst",
                 "quant_ab_probe"):
        assert name in entries, name
        assert entries[name]["tier"] in ("fast", "slow")
        assert os.path.exists(entries[name]["path"])
    assert catalog.scenario_path("fault_drill").endswith(
        os.path.join("scenarios", "fault_drill.json"))


def test_conductor_passes_the_concurrency_engine(tmp_path):
    """The reaper thread's lock discipline is a documented contract
    (poll outside the lock, event wakeups, join on stop) — the repo's
    own static race detector must find nothing in the conductor."""
    from tpu_resnet.analysis.concurrency import run_concurrency

    target = tmp_path / "conductor.py"
    shutil.copy(os.path.join(REPO, "tpu_resnet", "scenario",
                             "conductor.py"), target)
    assert run_concurrency(str(tmp_path)) == []
