"""Fleet autopilot (tpu_resnet/autopilot/; docs/AUTOPILOT.md).

Three layers, mirroring the subsystem's own split:

- pure policy: the decide() table driven by literal SignalSnapshots —
  hysteresis bands + streaks (no flap under an oscillating p99), both
  cooldowns (scale-down anchored on the LAST actuation in either
  direction), the colocation-admission backoff, min/max bounds and step
  clamps, blind-round streak resets, the shed high-water mark, and the
  bit-identical replay contract;
- controller/actuator units: run_round() driven synchronously with an
  injected collect_fn (scripted snapshots -> counters, integrators,
  gauges, the status file, the decision ledger), the admission-denied
  lifecycle through a fake actuator, spawn argv templating + the
  supervise wrap, LIFO drain targeting, the capacity-lease file;
- wiring: the conductor's ``autopilot`` process kind and the CLI's
  usage guard. The full subprocess drills live in
  scenarios/autoscale_*.json (doctor --autoscale-probe).
"""

import json
import os
import sys
import types

import pytest

from tpu_resnet.autopilot import signals
from tpu_resnet.autopilot.actuator import (Actuator, _Spawn,
                                           read_capacity_lease)
from tpu_resnet.autopilot.controller import (AUTOPILOT_STATUS_FILE,
                                             AutopilotController)
from tpu_resnet.autopilot.policy import (PolicyState, decide,
                                         effective_slo,
                                         note_admission_denied, replay)
from tpu_resnet.autopilot.signals import SignalSnapshot
from tpu_resnet.config import AutopilotConfig, load_config
from tpu_resnet.obs.fleet import (read_fleet_snapshot,
                                  write_fleet_snapshot,
                                  FLEET_SNAPSHOT_FILE)
from tpu_resnet.obs.server import parse_prometheus
from tpu_resnet.obs.spans import load_spans
from tpu_resnet.obs.trace import AUTOPILOT_EVENTS_FILE
from tpu_resnet.resilience import exitcodes


def _snap(wall, p99=None, healthy=1, pending=0, ok=True, shed=0.0,
          queue=0.0, burn=None, slo=0.0, replicas=(), port=None):
    return SignalSnapshot(
        wall=float(wall), ok=ok, p99_ms=p99, slo_ms=float(slo),
        replicas_healthy=healthy, replicas_pending=pending,
        replicas_total=healthy, shed_total=float(shed),
        queue_depth=float(queue), burn_fast=burn,
        replicas=tuple(replicas), router_port=port)


def _cfg(**kw):
    base = dict(slo_ms=100.0, up_rounds=2, down_rounds=3,
                min_replicas=1, max_replicas=4,
                scale_up_cooldown_secs=10.0,
                scale_down_cooldown_secs=60.0,
                admission_backoff_secs=30.0)
    base.update(kw)
    return AutopilotConfig(**base)


# ----------------------------------------------------------- pure policy
def test_scale_up_needs_a_full_pressure_streak():
    cfg = _cfg()
    state = PolicyState()
    d1, state = decide(_snap(0, p99=95), cfg, state)       # 95 > 90
    assert d1.action == "hold" and d1.pressure == "up"
    assert d1.reason == "pressure_up_building"
    d2, state = decide(_snap(1, p99=95), cfg, state)
    assert d2.action == "scale_up" and d2.reason == "p99"
    assert (d2.current, d2.target, d2.step) == (1, 2, 1)
    assert state.up_streak == 0 and state.last_up_wall == 1.0


def test_hysteresis_corridor_and_streaks_never_flap():
    """Two oscillation shapes that defeat single-threshold autoscalers:
    a p99 bouncing inside the dead zone (between the bands), and one
    alternating across the up band — neither may ever actuate."""
    cfg = _cfg()
    corridor, _ = replay([_snap(i, p99=60 if i % 2 else 85)
                          for i in range(30)], cfg)
    assert all(d.action == "hold" and d.pressure == "none"
               for d in corridor)
    alternating, _ = replay([_snap(i, p99=95 if i % 2 else 45, healthy=2)
                             for i in range(30)], cfg)
    assert all(d.action == "hold" for d in alternating)


def test_scale_up_cooldown_holds_then_releases():
    cfg = _cfg(up_rounds=1)
    state = PolicyState()
    d, state = decide(_snap(0, p99=150), cfg, state)
    assert d.action == "scale_up"
    d, state = decide(_snap(1, p99=150, healthy=2), cfg, state)
    assert d.action == "hold" and d.reason == "up_cooldown"
    d, state = decide(_snap(11, p99=150, healthy=2), cfg, state)
    assert d.action == "scale_up" and state.last_up_wall == 11.0


def test_scale_down_cooldown_anchors_on_last_actuation():
    """Capacity just added must survive a full scale-down cooldown —
    the anchor is max(last_up, last_down), not last_down alone."""
    cfg = _cfg(up_rounds=1, down_rounds=1)
    state = PolicyState()
    d, state = decide(_snap(0, p99=150), cfg, state)
    assert d.action == "scale_up"                # last_up_wall = 0
    d, state = decide(_snap(5, p99=20, healthy=2), cfg, state)
    assert d.action == "hold" and d.reason == "down_cooldown"
    d, state = decide(_snap(61, p99=20, healthy=2), cfg, state)
    assert d.action == "scale_down" and d.step == -1
    assert state.last_down_wall == 61.0
    d, state = decide(_snap(200, p99=20, healthy=1), cfg, state)
    assert d.action == "hold" and d.reason == "at_min"


def test_admission_backoff_delays_the_below_min_restore():
    """Exit-3 colocation denial arms the backoff; the floor restore
    waits it out, and pending spawns count toward current (no panic
    double-spawn while one is already en route)."""
    cfg = _cfg()
    state = note_admission_denied(PolicyState(), wall=0.0, cfg=cfg)
    assert state.denied_until == 30.0 and state.up_streak == 0
    d, state = decide(_snap(5, healthy=0), cfg, state)
    assert d.action == "hold" and d.reason == "admission_backoff"
    d, state = decide(_snap(31, healthy=0), cfg, state)
    assert d.action == "scale_up" and d.reason == "below_min"
    # A spawn in flight IS capacity: current = healthy + pending.
    d, state = decide(_snap(32, healthy=0, pending=1), cfg, state)
    assert d.action == "hold" and d.current == 1


def test_bounds_beat_everything_and_steps_clamp():
    cfg = _cfg(min_replicas=2, max_replicas=3, up_rounds=1)
    d, _ = decide(_snap(0, healthy=0), cfg, PolicyState())
    assert (d.action, d.reason, d.step) == ("scale_up", "below_min", 1)
    d, _ = decide(_snap(0, healthy=0),
                  _cfg(min_replicas=2, max_replicas=3, max_step_up=5),
                  PolicyState())
    assert d.step == 2 and d.target == 2         # clamped to the floor
    d, _ = decide(_snap(0, healthy=5), cfg, PolicyState())
    assert (d.action, d.reason, d.step) == ("scale_down", "above_max", -1)
    d, _ = decide(_snap(0, healthy=5),
                  _cfg(min_replicas=2, max_replicas=3, max_step_down=5),
                  PolicyState())
    assert d.step == -2 and d.target == 3        # clamped to the ceiling
    d, _ = decide(_snap(0, p99=150, healthy=3), cfg, PolicyState())
    assert d.action == "hold" and d.reason == "at_max"


def test_blind_rounds_hold_and_reset_streaks():
    cfg = _cfg()
    state = PolicyState()
    _, state = decide(_snap(0, p99=150), cfg, state)
    assert state.up_streak == 1
    d, state = decide(_snap(1, ok=False), cfg, state)
    assert d.action == "hold" and d.reason == "signals_unavailable"
    assert d.current == -1
    assert state.up_streak == 0 and state.down_streak == 0
    d, state = decide(_snap(2, p99=150), cfg, state)
    assert d.action == "hold"                    # streak restarts at 1
    d, state = decide(_snap(3, p99=150), cfg, state)
    assert d.action == "scale_up"


def test_shed_high_water_mark_fires_on_raises_only():
    """Cumulative router 429s: a RAISE since the last look is pressure,
    a flat counter is not — the high-water mark survives in state."""
    cfg = _cfg(slo_ms=0.0)                       # no latency signal
    state = PolicyState()
    d, state = decide(_snap(0, shed=5), cfg, state)
    assert d.pressure == "up" and state.shed_seen == 5.0
    d, state = decide(_snap(1, shed=5), cfg, state)
    assert d.pressure == "none"
    d, state = decide(_snap(2, shed=9), cfg, state)
    assert d.pressure == "up" and state.shed_seen == 9.0


def test_effective_slo_prefers_explicit_over_advertised():
    assert effective_slo(_snap(0, slo=250), _cfg(slo_ms=0.0)) == 250.0
    assert effective_slo(_snap(0, slo=250), _cfg(slo_ms=400.0)) == 400.0
    assert effective_slo(_snap(0), _cfg(slo_ms=0.0)) == 0.0


def test_replay_is_bit_identical_and_state_roundtrips():
    cfg = _cfg(up_rounds=1, down_rounds=2, scale_up_cooldown_secs=0.0,
               scale_down_cooldown_secs=5.0)
    trace = [_snap(0, p99=150), _snap(1, ok=False),
             _snap(2, p99=150, healthy=2, shed=3),
             _snap(3, p99=20, healthy=2), _snap(4, p99=20, healthy=2),
             _snap(10, p99=20, healthy=2), _snap(11, p99=20, healthy=2),
             _snap(12, p99=60, healthy=1), _snap(13, healthy=0)]
    first, end1 = replay(trace, cfg)
    second, end2 = replay(trace, cfg)
    assert first == second and end1 == end2      # frozen dataclasses
    assert [d.action for d in first].count("scale_up") >= 2
    assert "scale_down" in [d.action for d in first]
    assert PolicyState.from_dict(end1.to_dict()) == end1


# ------------------------------------------------------------ signals
def test_signal_snapshot_json_roundtrip():
    snap = _snap(7.5, p99=42.0, healthy=2, shed=3, port=8080,
                 replicas=[{"name": "r0", "state": "closed",
                            "draining": False, "pending": False,
                            "inflight": 1, "queue_depth": 0}])
    snap = SignalSnapshot(**{**snap.__dict__,
                             "errors": ("router /info: timeout",),
                             "hbm": (("r0", {"hbm_bytes_in_use": 5.0,
                                             "hbm_bytes_limit": 10.0}),)})
    wire = json.loads(json.dumps(snap.to_dict()))
    back = SignalSnapshot.from_dict(wire)
    # from_dict keeps replicas as dicts inside the tuple — compare field
    # by field through to_dict, the serialization contract itself.
    assert back.to_dict() == snap.to_dict()
    assert back.wall == 7.5 and back.errors == snap.errors


def test_collect_on_empty_dir_is_an_explicit_blind_round(tmp_path):
    snap = signals.collect(str(tmp_path))
    assert not snap.ok
    assert "route.json" in snap.errors[0]


def test_fleet_snapshot_digest_gates_reads(tmp_path):
    d = str(tmp_path)
    assert read_fleet_snapshot(d) is None
    write_fleet_snapshot(d, {"round": 3, "fleet": {"p99_ms": 12.5}})
    body = read_fleet_snapshot(d)
    assert body["round"] == 3 and body["fleet"]["p99_ms"] == 12.5
    # A hand edit keeps the old digest: the read must refuse it.
    path = os.path.join(d, FLEET_SNAPSHOT_FILE)
    with open(path) as f:
        tampered = json.load(f)
    tampered["round"] = 99
    with open(path, "w") as f:
        json.dump(tampered, f)
    assert read_fleet_snapshot(d) is None


def test_loadgen_diurnal_schedule_is_bounded_and_deterministic():
    from tools.loadgen import SCENARIOS, qps_factor

    assert "diurnal" in SCENARIOS
    vals = [qps_factor("diurnal", i / 200.0) for i in range(201)]
    assert all(0.05 <= v <= 1.6 + 1e-9 for v in vals)
    assert vals == [qps_factor("diurnal", i / 200.0) for i in range(201)]
    assert qps_factor("diurnal", 0.0) == pytest.approx(0.3)
    assert max(vals) > 1.1 and min(vals) < 0.25   # real up AND down swings


# ----------------------------------------------------------- controller
def _ctl_cfg(tmp_path, **auto):
    cfg = load_config()
    cfg.autopilot.discover_dir = str(tmp_path)
    cfg.autopilot.slo_ms = 100.0
    cfg.autopilot.up_rounds = 2
    cfg.autopilot.min_replicas = 1
    cfg.autopilot.max_replicas = 4
    for k, v in auto.items():
        setattr(cfg.autopilot, k, v)
    return cfg


def test_controller_round_counters_gauges_status_and_ledger(tmp_path):
    """Three scripted rounds (hold -> scale_up -> blind) through the
    real controller in observe-only mode: the counters, the integrators
    (snapshot time, not wall time), the gauges, autopilot_status.json
    and the decision ledger all describe the same rounds."""
    trace = [_snap(0, p99=150), _snap(1, p99=150), _snap(2, ok=False)]
    it = iter(trace)
    ctl = AutopilotController(_ctl_cfg(tmp_path),
                              collect_fn=lambda: next(it))
    try:
        assert ctl.run_round().action == "hold"
        assert ctl.run_round().action == "scale_up"
        assert ctl.run_round().reason == "signals_unavailable"
        status = ctl.status()
        c = status["counters"]
        assert c["rounds"] == 3 and c["scale_ups"] == 1
        assert c["holds"] == 2 and c["signal_errors"] == 1
        assert c["spawns"] == 0                  # observe-only
        assert status["target"] == 2
        # Integrators ride snapshot walls: exactly one 1s interval, all
        # of it above the SLO.
        assert status["replica_seconds"] == 1.0
        assert status["slo_violation_seconds"] == 1.0
        gauges = parse_prometheus(ctl.registry.render())
        assert gauges["tpu_resnet_autopilot_rounds_total"] == 3.0
        assert gauges["tpu_resnet_autopilot_target_replicas"] == 2.0
        assert gauges["tpu_resnet_autopilot_scale_ups_total"] == 1.0
        with open(os.path.join(str(tmp_path),
                               AUTOPILOT_STATUS_FILE)) as f:
            on_disk = json.load(f)
        assert on_disk["counters"] == c
        assert on_disk["decision"]["reason"] == "signals_unavailable"
    finally:
        ctl.close()
    spans = load_spans(os.path.join(str(tmp_path),
                                    AUTOPILOT_EVENTS_FILE))
    decisions = [s for s in spans if s["span"] == "autopilot_decision"]
    assert [s["action"] for s in decisions] == ["hold", "scale_up",
                                                "hold"]
    assert decisions[0]["reason"] == "pressure_up_building"


class _FakeActuator:
    """Scripted lifecycle events + recorded spawns; observe_only False
    so the controller exercises the real actuation branch."""

    observe_only = False
    lease_granted = False

    def __init__(self, events):
        self.events = list(events)
        self.spawned = []

    def pending_count(self):
        return 0

    def poll(self, snapshot):
        return self.events.pop(0) if self.events else []

    def spawn_replica(self):
        self.spawned.append(f"ap{len(self.spawned)}")
        return {"name": self.spawned[-1], "pid": 4000 + len(self.spawned)}

    def close(self):
        pass


def test_controller_admission_denied_then_backoff_then_spawn(tmp_path):
    """The full exit-3 story: a denial event arms the policy backoff
    (the below-min restore HOLDS), and once the backoff lapses the
    floor is restored through a real spawn_replica() call."""
    denial = [{"kind": "admission_denied", "name": "ap0", "rc": 3}]
    fake = _FakeActuator([denial, []])
    trace = [_snap(0, healthy=0), _snap(40, healthy=0)]
    it = iter(trace)
    ctl = AutopilotController(_ctl_cfg(tmp_path),
                              collect_fn=lambda: next(it),
                              actuator=fake)
    try:
        d1 = ctl.run_round()
        assert d1.action == "hold" and d1.reason == "admission_backoff"
        d2 = ctl.run_round()
        assert d2.action == "scale_up" and d2.reason == "below_min"
        assert fake.spawned == ["ap0"]
        c = ctl.status()["counters"]
        assert c["admission_denied"] == 1 and c["spawns"] == 1
    finally:
        ctl.close()
    kinds = [s["span"] for s in load_spans(
        os.path.join(str(tmp_path), AUTOPILOT_EVENTS_FILE))]
    assert "autopilot_admission_denied" in kinds
    assert "autopilot_spawn" in kinds


def test_controller_admitted_spawn_is_not_also_counted_pending(tmp_path):
    """The round that first sees a spawn healthy in the router must not
    ALSO count it as pending: current = healthy + pending would read
    3 with max_replicas=2 and the above_max bound (which rightly skips
    cooldowns) would drain the replica the moment it was admitted — an
    admit/drain flap loop. poll() runs before replicas_pending is
    stamped, so the spawn graduates within the round."""
    cfg = _ctl_cfg(tmp_path, max_replicas=2)
    ctl = AutopilotController(
        cfg, collect_fn=lambda: _snap(
            10.0, p99=300, healthy=2,
            replicas=({"name": "r0", "state": "closed",
                       "draining": False, "pending": False},
                      {"name": "ap0", "state": "closed",
                       "draining": False, "pending": False})))
    try:
        # One in-flight spawn, launched earlier, now healthy above.
        ctl.actuator._spawns.append(_Spawn(
            "ap0", types.SimpleNamespace(
                poll=lambda: None, terminate=lambda: None,
                kill=lambda: None, wait=lambda timeout=None: 0),
            8.0, ""))
        assert ctl.actuator.pending_count() == 1
        d = ctl.run_round()
        assert d.action == "hold"             # NOT above_max scale_down
        assert d.current == 2                 # not 3
        assert ctl.actuator.pending_count() == 0
        assert ctl.status()["scale_up_latency_ms"] == 2000.0
    finally:
        ctl.close()
    spans = load_spans(os.path.join(str(tmp_path),
                                    AUTOPILOT_EVENTS_FILE))
    ready = [s for s in spans if s["span"] == "autopilot_replica_ready"]
    assert len(ready) == 1 and ready[0]["name"] == "ap0"
    decision = [s for s in spans
                if s["span"] == "autopilot_decision"][-1]
    assert decision["replicas_pending"] == 0


# ------------------------------------------------------------- actuator
def test_actuator_builds_supervised_argv_from_template(tmp_path):
    cfg = load_config()
    cfg.autopilot.spawn_cmd = ("{python} -m tpu_resnet serve "
                               "serve.replica_name={name} data.seed={i}")
    act = Actuator(cfg, str(tmp_path), spans=None)
    argv = act._build_argv("ap0", 0)
    assert argv[0] == sys.executable
    assert argv[1].endswith(os.path.join("tools", "supervise.py"))
    stop = argv.index("--stop-codes")
    assert argv[stop + 1] == str(exitcodes.NO_CAPACITY)
    tail = argv[argv.index("--") + 1:]
    assert tail == [sys.executable, "-m", "tpu_resnet", "serve",
                    "serve.replica_name=ap0", "data.seed=0"]
    cfg.autopilot.spawn_supervised = False
    assert act._build_argv("ap7", 7) == [
        sys.executable, "-m", "tpu_resnet", "serve",
        "serve.replica_name=ap7", "data.seed=7"]


def test_actuator_drain_target_is_lifo_owned_first(tmp_path):
    act = Actuator(load_config(), str(tmp_path), spans=None)

    def rec(name):
        return {"name": name, "state": "closed", "draining": False,
                "pending": False}

    snap = types.SimpleNamespace(
        replicas=(rec("r0"), rec("ap0"), rec("ap1")))
    # No owned spawns yet: fall back to the lexicographically-last
    # healthy external replica.
    assert act.pick_drain_target(snap) == "r0"
    for name in ("ap0", "ap1"):
        act._spawns.append(_Spawn(name, types.SimpleNamespace(), 0.0, ""))
    assert act.pick_drain_target(snap) == "ap1"   # newest owned first
    act._spawns[-1].done = True
    assert act.pick_drain_target(snap) == "ap0"
    empty = types.SimpleNamespace(replicas=())
    assert act.pick_drain_target(empty) is None


def test_capacity_lease_grant_and_revoke_roundtrip(tmp_path):
    d = str(tmp_path)
    act = Actuator(load_config(), d, spans=None, clock=lambda: 123.0)
    assert read_capacity_lease(d) is None
    act.grant_lease(2)
    lease = read_capacity_lease(d)
    assert lease["state"] == "granted" and lease["holder"] == "trainer"
    assert lease["freed_replicas"] == 2 and lease["wall"] == 123.0
    assert act.lease_granted
    act.revoke_lease()
    assert read_capacity_lease(d)["state"] == "revoked"
    assert not act.lease_granted


# --------------------------------------------------------------- wiring
def test_conductor_runs_autopilot_as_a_module_kind():
    from tpu_resnet.scenario.conductor import _build_argv
    from tpu_resnet.scenario.spec import PROC_KINDS

    assert "autopilot" in PROC_KINDS
    argv = _build_argv({"kind": "autopilot", "preset": "smoke",
                        "overrides": {"autopilot.min_replicas": 1}},
                       root="/root/repo")
    assert argv[:4] == [sys.executable, "-m", "tpu_resnet", "autopilot"]
    assert argv[4:6] == ["--preset", "smoke"]
    assert "autopilot.min_replicas=1" in argv


def test_cli_refuses_to_run_without_a_fleet_directory():
    from tpu_resnet.autopilot.cli import autopilot

    cfg = load_config()
    cfg.autopilot.discover_dir = ""
    cfg.train.train_dir = ""
    assert autopilot(cfg) == exitcodes.USAGE_ERROR
