"""The compile-smoke prelude (tools/pallas_compile_smoke.py) and the
battery stages' skip logic around it (VERDICT r4 item 3): a Mosaic
lowering failure on the first live window must cost ~a minute and yield
the window to the headline bench — not burn the 1800 s A/B budget."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SMOKE = os.path.join(REPO, "tools", "pallas_compile_smoke.py")


def _run_smoke(tmp_path, family, extra=()):
    """Always under the scrubbed CPU env: the ambient env carries the
    axon TPU plugin, and importing jax there HANGS when the tunnel is
    down — a test must never block on tunnel state."""
    from tpu_resnet.hostenv import scrubbed_cpu_env

    out = tmp_path / f"smoke_{family}.json"
    proc = subprocess.run(
        [sys.executable, SMOKE, "--family", family, "--out", str(out),
         *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600, cwd=REPO, env=scrubbed_cpu_env(1))
    return proc, (json.loads(out.read_text()) if out.exists() else None)


def test_smoke_block_interpret_passes_oracle(tmp_path):
    """Interpret mode runs anywhere: all four block directions compile
    (as XLA ops on CPU) and match the oracle."""
    proc, art = _run_smoke(tmp_path, "block", ("--interpret",))
    assert proc.returncode == 0, proc.stdout
    assert art["compile_ok"] is True
    assert set(art["checks"]) == {"fwd_max_err", "bwd_max_err",
                                  "train_fwd_max_err", "train_bwd_max_err"}
    assert all(v < 2e-2 for v in art["checks"].values())


def test_smoke_bottleneck_interpret_passes_oracle(tmp_path):
    proc, art = _run_smoke(tmp_path, "bottleneck", ("--interpret",))
    assert proc.returncode == 0, proc.stdout
    assert art["compile_ok"] is True
    assert set(art["checks"]) == {"fwd_max_err", "bwd_max_err"}


def test_smoke_failure_writes_gate_compatible_artifact(tmp_path):
    """Non-interpret mode on the scrubbed CPU backend: whatever Pallas
    does there, the smoke must produce a gate-compatible verdict — a
    clean pass (some jax versions lower Pallas natively on CPU), or exit
    1 with the error captured and compile_ok=false + empty by_shape (the
    shape ab_gate reads as a standing loss)."""
    proc, art = _run_smoke(tmp_path, "block")  # non-interpret on CPU
    if proc.returncode == 0:
        # The forced-failure stage path is covered by the
        # COMPILE_SMOKE_FORCE tests below either way.
        assert art["compile_ok"] is True
        return
    assert art["compile_ok"] is False
    assert art["error"]
    assert art["by_shape"] == {}
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import ab_gate
    gate_art = tmp_path / "smoke_block.json"
    assert ab_gate.main(["ab_gate", str(gate_art)]) == 1  # standing loss


def _run_stage(name, tmp_path, env_extra):
    out = tmp_path / "out"
    out.mkdir(exist_ok=True)
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(
        ["bash", os.path.join(REPO, "tools", "battery.d", name), str(out)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=120, cwd=REPO, env=env)


def test_stage05_smoke_failure_archives_and_yields(tmp_path):
    """Forced smoke failure: stage 05 exits 0 (done — the battery falls
    through to stage 10) with the failure archived as the A/B artifact,
    which the downstream gates read as a measured loss."""
    smoke = tmp_path / "smoke.json"
    ab_out = tmp_path / "ab.json"
    proc = _run_stage("05_fused_block_ab.sh", tmp_path, {
        "COMPILE_SMOKE_FORCE": "fail",
        "COMPILE_SMOKE_OUT": str(smoke),
        "FUSED_BLOCK_AB_OUT": str(ab_out)})
    assert proc.returncode == 0
    assert "A/B skipped" in proc.stdout
    art = json.loads(ab_out.read_text())
    assert art["compile_ok"] is False
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import ab_gate
    assert ab_gate.main(["ab_gate", str(ab_out)]) == 1


def test_stage05_smoke_timeout_retries(tmp_path):
    """A smoke timeout is a tunnel flake, not infeasibility: the stage
    must stay armed (exit 1) and archive nothing."""
    ab_out = tmp_path / "ab.json"
    proc = _run_stage("05_fused_block_ab.sh", tmp_path, {
        "COMPILE_SMOKE_FORCE": "timeout",
        "COMPILE_SMOKE_OUT": str(tmp_path / "smoke.json"),
        "FUSED_BLOCK_AB_OUT": str(ab_out)})
    assert proc.returncode == 1
    assert "retry" in proc.stdout
    assert not ab_out.exists()


def test_stage55_smoke_failure_archives_and_yields(tmp_path):
    """Same discipline for the bottleneck stage — with its 05 gate fed a
    winning artifact so the stage reaches the smoke."""
    gate05 = tmp_path / "win05.json"
    gate05.write_text(json.dumps(
        {"by_shape": {"s": {"fwd": {"speedup": 1.3}}}}))
    ab_out = tmp_path / "ab55.json"
    proc = _run_stage("55_fused_bottleneck_ab.sh", tmp_path, {
        "FUSED_AB_GATE": str(gate05),
        "COMPILE_SMOKE_FORCE": "fail",
        "COMPILE_SMOKE_OUT": str(tmp_path / "smoke55.json"),
        "FUSED_BOTTLENECK_AB_OUT": str(ab_out)})
    assert proc.returncode == 0
    assert "A/B skipped" in proc.stdout
    assert json.loads(ab_out.read_text())["compile_ok"] is False
