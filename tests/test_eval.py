"""Evaluator tests — checkpoint-polling sidecar parity
(reference resnet_cifar_eval.py:85-143) on the virtual 8-device mesh."""

import jax
import numpy as np

from tpu_resnet.config import load_config
from tpu_resnet.evaluation.evaluator import (
    _mesh_eval_batch,
    build_eval_step,
    evaluate,
    run_eval_pass,
)
from tpu_resnet.parallel import create_mesh, replicated
from tpu_resnet.train import build_schedule, init_state, train
import jax.numpy as jnp



def test_eval_batch_rounded_to_mesh():
    cfg = load_config("smoke")
    cfg.train.eval_batch_size = 100  # reference default, not divisible by 8
    mesh = create_mesh(cfg.mesh)
    assert _mesh_eval_batch(cfg, mesh) == 104
    cfg.train.eval_batch_size = 64
    assert _mesh_eval_batch(cfg, mesh) == 64


def test_run_eval_pass_counts_every_example():
    cfg = load_config("smoke")
    cfg.train.eval_batch_size = 100  # forces padding + rounding paths
    mesh = create_mesh(cfg.mesh)
    model, eval_step = build_eval_step(cfg, mesh)
    sched = build_schedule(cfg.optim, cfg.train)
    state = init_state(model, cfg.optim, sched, jax.random.PRNGKey(0),
                       jnp.zeros((1, 32, 32, 3)))
    state = jax.device_put(state, replicated(mesh))
    precision, loss, count = run_eval_pass(cfg, state, mesh, eval_step)
    assert 0.0 <= precision <= 1.0
    assert np.isfinite(loss)
    # Every example of the synthetic eval split is counted exactly once
    # (the reference sampled only half the CIFAR test set).
    assert count == cfg.data.eval_examples


def test_evaluate_once_end_to_end(tmp_path):
    """train → eval --once → Precision/Best_Precision written
    (the reference's train+eval sidecar pair, on one mesh)."""
    cfg = load_config("smoke")
    cfg.train.train_dir = str(tmp_path / "run")
    cfg.train.train_steps = 6
    cfg.train.checkpoint_every = 3
    cfg.train.log_every = 3
    cfg.train.global_batch_size = 16
    cfg.train.eval_once = True
    train(cfg)
    precision = evaluate(cfg)
    assert precision is not None
    import json, os
    best = json.load(open(os.path.join(cfg.train.train_dir, "eval",
                                       "best_precision.json")))
    assert best["step"] == 6
    assert best["best_precision"] == precision


def test_evaluate_once_no_checkpoint_returns_none(tmp_path):
    cfg = load_config("smoke")
    cfg.train.train_dir = str(tmp_path / "empty")
    cfg.train.eval_once = True
    assert evaluate(cfg) is None
