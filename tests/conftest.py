"""Test harness: run everything on a virtual 8-device CPU mesh — the JAX
analog of the reference's localhost fake-cluster trick
(mkl-scripts/submit_mac_dist.sh: 1 ps + 2 workers on localhost ports), per
SURVEY.md §4."""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
