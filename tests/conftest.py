"""Test harness: run everything on a virtual 8-device CPU mesh — the JAX
analog of the reference's localhost fake-cluster trick
(mkl-scripts/submit_mac_dist.sh: 1 ps + 2 workers on localhost ports), per
SURVEY.md §4."""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: repeat suite runs skip recompiling the
# (identical) test programs — the dominant cost of the suite on this
# single-core box. Keyed by backend+program, so source changes that alter a
# program recompile as usual. Opt out with TPU_RESNET_TEST_CACHE=0.
if os.environ.get("TPU_RESNET_TEST_CACHE", "1") != "0":
    _cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(__file__), ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # Subprocess-spawning tests (multihost rendezvous, launcher dryruns)
    # pick the cache up from the environment.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
