"""Test harness: run everything on a virtual 8-device CPU mesh — the JAX
analog of the reference's localhost fake-cluster trick
(mkl-scripts/submit_mac_dist.sh: 1 ps + 2 workers on localhost ports), per
SURVEY.md §4."""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: repeat suite runs skip recompiling the
# (identical) test programs — the dominant cost of the suite on this
# single-core box. Keyed by backend+program, so source changes that alter a
# program recompile as usual.
#
# DEFAULT OFF (opt in with TPU_RESNET_TEST_CACHE=1): this jaxlib's CPU
# executable deserialization is unsafe. Observed, reproducibly, with a warm
# cache: (a) hard SIGSEGV on the second in-process deserialization of a
# fused-chunk entry (train()+resume constructs a fresh jit wrapper, so the
# same entry deserializes twice — crash at the resume's first dispatch);
# (b) worse, a SILENTLY WRONG executable served from cache: a resumed run
# whose host loop provably stopped at step 14 (events.jsonl run span,
# checkpoint label) returned device state.step == 16 — cached-executable
# corruption, not a loop bug (checkpoints 5/10 from the same run carry
# exact step contents; the miscount appears only with the cache enabled
# and is nondeterministic across runs). Wrong-result risk rules the cache
# out as a default; the stamp/DIRTY hygiene below is kept for opt-in use
# on a jaxlib whose deserialization is trustworthy.
if os.environ.get("TPU_RESNET_TEST_CACHE", "0") == "1":
    _cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(__file__), ".jax_cache"))
    # Cache entries serialize compiled executables; deserializing entries
    # written by a DIFFERENT jaxlib hard-crashes the process (observed:
    # deterministic SIGSEGV mid-suite after a jaxlib bump). Stamp the cache
    # with the producing jaxlib version and wipe it on mismatch — the run
    # then repopulates it with loadable entries.
    import glob as _glob
    import jaxlib

    _stamp = os.path.join(_cache_dir, "JAXLIB_VERSION")
    _want = jaxlib.__version__
    try:
        with open(_stamp) as _f:
            _have = _f.read().strip()
    except OSError:
        _have = None
    if _have != _want:
        for _p in _glob.glob(os.path.join(_cache_dir, "*-cache")) + \
                _glob.glob(os.path.join(_cache_dir, "*-atime")):
            try:
                os.remove(_p)
            except OSError:
                pass
        os.makedirs(_cache_dir, exist_ok=True)
        with open(_stamp, "w") as _f:
            _f.write(_want + "\n")
    # Same jaxlib can still poison the cache: a run killed hard mid-write
    # (SIGSEGV, `timeout -k` KILL) leaves a torn entry that deterministically
    # segfaults every later deserialization (observed: resident-path
    # executable). Mark the cache busy for the run's duration; a mark still
    # present at startup means the previous run died mid-suite — wipe and
    # let this run repopulate. (A concurrent second pytest can at worst
    # trigger a spurious wipe: recompilation, never a failure.)
    import atexit as _atexit

    _dirty = os.path.join(_cache_dir, "DIRTY")
    if os.path.exists(_dirty):
        for _p in _glob.glob(os.path.join(_cache_dir, "*-cache")) + \
                _glob.glob(os.path.join(_cache_dir, "*-atime")):
            try:
                os.remove(_p)
            except OSError:
                pass
    os.makedirs(_cache_dir, exist_ok=True)
    with open(_dirty, "w") as _f:
        _f.write(str(os.getpid()) + "\n")

    def _clear_dirty(path=_dirty):
        try:
            os.remove(path)
        except OSError:
            pass

    _atexit.register(_clear_dirty)
    # Quarantine the fused-chunk executables from warm reuse: a train()+
    # resume flow constructs a fresh jit wrapper for the same chunk
    # program, so the warm entry is DESERIALIZED TWICE in one process —
    # and the second deserialization segfaults this jaxlib's CPU runtime
    # (reproduced standalone: two train() calls over a warm cache crash at
    # the resume's first dispatch; single-deserialization flows reload
    # fine). Deleting the family at session start forces chunk programs to
    # recompile each run while every other entry stays warm.
    for _p in _glob.glob(os.path.join(_cache_dir, "jit_chunk-*")):
        try:
            os.remove(_p)
        except OSError:
            pass
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # Subprocess-spawning tests (multihost rendezvous, launcher dryruns)
    # pick the cache up from the environment.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
