"""PRE-FIX PR 5 admission race (seeded fixture — this is the bug shape
review caught by hand: the engine must catch it mechanically).

``submit`` (HTTP handler threads) checks the accepting flag bare and
puts; ``drain`` (main thread) flips the flag bare and flushes only what
it can see. A submit racing the flip lands its request AFTER the final
flush and the client hangs for the full wait timeout instead of getting
an immediate 503. The fixed code serializes both sides under an
admission lock (tpu_resnet/serve/batcher.py ``_admit_lock``).
"""

import queue
import threading


class Draining(Exception):
    pass


class MicroBatcher:
    def __init__(self, infer_fn):
        self._infer = infer_fn
        self._queue = queue.Queue(maxsize=16)
        self._accepting = True
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, images):
        # BUG: bare check-then-put — the drain flip can interleave here.
        if not self._accepting:
            raise Draining("server is draining")
        self._queue.put_nowait(images)

    def _run(self):
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            self._infer(item)

    def drain(self):
        # BUG: unlocked flag flip racing submit's unlocked check.
        self._accepting = False
        self._stop.set()
        self._thread.join(timeout=5)
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
