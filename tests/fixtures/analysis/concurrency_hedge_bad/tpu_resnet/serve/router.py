"""PRE-FIX PR 11 hedge attribution (seeded fixture).

The hedged send spawns daemon legs that write the router's breaker
bookkeeping (failure counter, last_error) directly from the leg
threads, while ``route_predict`` writes the same fields on its own
thread — a failed hedge leg double-charges (or mis-charges) the primary
replica's breaker and opens a healthy replica's circuit. The fixed code
attributes every leg's result exactly once through the results queue
and charges inside one owner (_attempt), under the router lock.
"""

import queue
import threading


class Router:
    def __init__(self, forward):
        self._forward = forward
        self._lock = threading.Lock()
        self.replica_errors = 0
        self.last_error = None

    def _attempt(self, replica, body):
        results = queue.Queue()

        def call(rep, who):
            try:
                results.put((who, self._forward(rep, body)))
            except OSError as e:
                # BUG: breaker bookkeeping written from the hedge-leg
                # thread, racing route_predict's own writes.
                self.replica_errors += 1
                self.last_error = str(e)
                results.put((who, e))

        threading.Thread(target=call, args=(replica, "primary"),
                         daemon=True).start()
        threading.Thread(target=call, args=(replica, "hedge"),
                         daemon=True).start()
        return results.get(timeout=1.0)

    def route_predict(self, replica, body):
        try:
            who, res = self._attempt(replica, body)
        except OSError as e:
            # BUG: same fields, another thread, no lock — double charge.
            self.replica_errors += 1
            self.last_error = str(e)
            return None
        return res
