"""Seeded host-isolation + signal-safety violations for the fleet
router. The real serve/router.py is stdlib-only (it must come up on a
host whose accelerator stack is broken) and delegates SIGTERM to the
flag-only ShutdownCoordinator; this fixture is the pair of anti-patterns
that must stay flagged: a module-scope jax import, and a handler that
tears the fleet down inline instead of setting a flag for route()."""

import signal
import time

import jax  # host-isolation: the router must never import jax


class EagerTeardownRouter:
    """'Just drain the fleet right here in the handler' — every call
    below runs at an arbitrary bytecode boundary of the interrupted
    prober/forwarder threads."""

    def __init__(self, httpd, prober, replicas):
        self._httpd = httpd
        self._prober = prober
        self._replicas = replicas

    def install(self):
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        self._teardown_now(signum)  # transitively unsafe

    def _teardown_now(self, signum):
        for replica in self._replicas:
            self.drain_replica(replica.name)  # flagged: joins + signals
        time.sleep(0.5)                       # flagged: sleep in handler
        self._httpd.shutdown()                # flagged: socket teardown
        self._prober.join()                   # flagged: thread join

    def drain_replica(self, name):
        return jax.device_count(), name
