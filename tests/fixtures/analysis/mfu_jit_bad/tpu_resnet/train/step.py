"""Seeded jit-host-sync violation (fixture for tests/test_analysis.py):
MFU cost-analysis introspection inside the jitted hot path.

obs/mfu.py's accounting (.lower().cost_analysis()) is a one-time host
startup cost; calling it per step from jit scope re-traces the program
on every dispatch. The rule must flag it here (jit-scope path)."""


def make_train_step(step_fn, state, images, labels):
    def train_step(state, images, labels):
        # Per-step compile introspection: must be flagged.
        flops = step_fn.lower(state, images, labels).cost_analysis()
        new_state, metrics = step_fn(state, images, labels)
        metrics["flops"] = flops
        return new_state, metrics

    return train_step
