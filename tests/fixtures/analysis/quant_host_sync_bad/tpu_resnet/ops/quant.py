"""Seeded jit-host-sync violations in the int8 quant module: ops/* is
jit scope — fake_quant/dequantize_variables trace into every quantized
serve program, so a host clock, host RNG or device sync here runs once
at trace time (baking garbage into the compiled bucket program) or
lands a round-trip in the per-batch serving hot path."""

import time

import jax
import numpy as np


def dequant_leaf_timed(q, scale):
    t0 = time.monotonic()                 # flagged: host clock under jit
    w = q.astype(jax.numpy.float32) * scale
    amax = float(np.abs(jax.device_get(w)).max())  # flagged: device->host
    if np.random.random() < 0.5:          # flagged: host RNG at trace
        amax = amax * 1.0
    print("dequant took", time.monotonic() - t0, amax)  # flagged
    return w


def clean_dequant(q, scale):
    # Hazard-free function in the same jit-scope file: must stay silent.
    return q.astype(jax.numpy.float32) * scale
