"""Seeded jit-host-sync violation (fixture for tests/test_analysis.py):
device-memory introspection inside the jitted hot path.

obs/memory.py's gauges (device.memory_stats()) and OOM forensics
(jax.live_arrays()) are host-side log-boundary/crash-handler calls; from
jit scope memory_stats is a per-dispatch host RPC into the PJRT client
and live_arrays walks every live buffer. The rule must flag all three
(memory_analysis is the ledger's compile-introspection marker).
"""
import jax


def make_train_step(step_fn, state, images, labels):
    def train_step(state, images, labels):
        # Per-step memory introspection: all three must be flagged.
        stats = jax.local_devices()[0].memory_stats()
        census = jax.live_arrays()
        budget = step_fn.lower(state, images, labels).compile().memory_analysis()
        new_state, metrics = step_fn(state, images, labels)
        metrics["hbm"] = (stats, len(census), budget)
        return new_state, metrics

    return train_step
