"""Seeded registry-scope violation: a helper jitting programs directly
instead of routing them through tpu_resnet/programs/registry.py — the
bypass pattern the registry-scope lint exists to catch (such a program
is invisible to the key spelling, the golden engines AND the persistent
AOT executable cache, so it re-pays cold-start compiles forever)."""

import jax
from jax.experimental.pjit import pjit


def sneaky_speedup(fn):
    # call-form construction outside the registry scope
    return jax.jit(fn, static_argnums=(1,))


@jax.jit
def decorated_square(x):
    # decorator-form construction outside the registry scope
    return x * x


def sharded_apply(fn, in_shardings, out_shardings):
    # the pjit spelling must be caught too
    return pjit(fn, in_shardings=in_shardings,
                out_shardings=out_shardings)
