"""False-positive suite: four threaded patterns that are CORRECT and
must produce zero concurrency findings — the exemption logic is as much
of the contract as the rules.

- queue-channel: threads communicate only through Queue/Event objects
  (their methods ARE the synchronization).
- immutable-after-start: configuration written in ``__init__`` only,
  read freely from every context.
- lock-free single-writer ring: one thread writes the cursor, nothing
  else touches it.
- atomic publish: every write guarded, the hot-path read bare (the
  serve backend's ``_variables`` idiom).
"""

import queue
import threading


class QueueChannel:
    """Threads exchange work through channels only."""

    def __init__(self, fn):
        self._fn = fn
        self._tasks = queue.Queue()
        self._results = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, item):
        self._tasks.put(item)

    def take(self):
        return self._results.get()

    def _run(self):
        while not self._stop.is_set():
            try:
                item = self._tasks.get(timeout=0.05)
            except queue.Empty:
                continue
            self._results.put(self._fn(item))

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)


class ImmutableAfterStart:
    """Config assigned before the thread starts, then only read."""

    def __init__(self, interval, sink):
        self.interval = float(interval)
        self._sink = sink
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval):
            self._sink(self.interval)

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)


class SingleWriterRing:
    """Only the producer thread moves the write cursor."""

    def __init__(self, slots):
        self._slots = [None] * slots
        self._head = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _produce(self):
        while not self._stop.is_set():
            self._slots[self._head % len(self._slots)] = object()
            self._head += 1

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)


class AtomicPublish:
    """Writes serialized under the lock; the hot-path read is a single
    reference load (the documented lock-free consumer)."""

    def __init__(self, loader):
        self._loader = loader
        self._lock = threading.Lock()
        self._value = None
        self._thread = threading.Thread(target=self._reload, daemon=True)
        self._thread.start()

    def _reload(self):
        v = self._loader()
        with self._lock:
            self._value = v

    def get(self):
        return self._value

    def refresh(self):
        with self._lock:
            self._value = self._loader()
