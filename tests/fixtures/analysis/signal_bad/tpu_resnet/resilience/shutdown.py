"""Seeded signal-safety violations: a handler that does real work
(checkpoint save, file I/O, lock, sleep) instead of setting a flag."""

import signal
import threading
import time


class EagerShutdown:
    """The anti-pattern: 'just save right here in the handler'."""

    def __init__(self, ckpt, train_dir):
        self._ckpt = ckpt
        self._train_dir = train_dir
        self._lock = threading.Lock()
        self._event = threading.Event()

    def install(self):
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        self._event.set()               # fine
        self._finalize(signum)          # transitively unsafe

    def _finalize(self, signum):
        self._lock.acquire()            # flagged: lock in handler path
        self._ckpt.save(0, force=True)  # flagged: checkpoint save
        with open(self._train_dir + "/stop", "w") as fh:  # flagged: open
            fh.write(str(signum))
        time.sleep(0.5)                 # flagged: sleep in handler
