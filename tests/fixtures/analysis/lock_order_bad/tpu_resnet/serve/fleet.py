"""Seeded lock-order fixtures: the ABBA deadlock (two methods taking
the same two locks in opposite orders) and the non-reentrant
self-deadlock (a Lock re-acquired on a path that already holds it,
directly and through a method call)."""

import threading


class FleetState:
    def __init__(self):
        self._replica_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.replicas = {}
        self.stats = {}

    def admit(self, name):
        # Order: replica -> stats
        with self._replica_lock:
            self.replicas[name] = True
            with self._stats_lock:
                self.stats[name] = 0

    def report(self):
        # BUG: order stats -> replica — deadlocks against admit().
        with self._stats_lock:
            out = dict(self.stats)
            with self._replica_lock:
                out["replicas"] = len(self.replicas)
        return out


class Member:
    def __init__(self):
        self._member_lock = threading.Lock()
        self.load = 0
        self.fleet = None

    def rebalance(self):
        # Order: member -> fleet (via the fleet's locked method).
        with self._member_lock:
            self.load = 0
            self.fleet.note_admit("self")


class FleetView:
    """BUG (cross-class ABBA): holds the fleet-view lock while calling
    into Member, whose rebalance() holds ITS lock while calling back
    into a fleet-view-locked method — two objects, opposite orders."""

    def __init__(self, member):
        self._view_lock = threading.Lock()
        self.member = member
        self.totals = {}

    def note_admit(self, name):
        with self._view_lock:
            self.totals[name] = 1

    def refresh(self):
        # Order: fleet-view -> member.
        with self._view_lock:
            self.member.rebalance()


class Reacquirer:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def _bump(self):
        with self._lock:
            self.n += 1

    def bump_twice(self):
        # BUG: non-reentrant Lock re-acquired through a call while held.
        with self._lock:
            self._bump()

    def bump_nested(self):
        # BUG: direct lexical re-acquisition — immediate self-deadlock.
        with self._lock:
            with self._lock:
                self.n += 1
