"""Worker entry module with two seeded fork-safety violations: the
parent-package eager import reaches jax transitively, and the process
pool uses the platform-default fork context."""

import multiprocessing
import threading

from tpu_resnet.data import ShardedBatcher  # closure -> pipeline -> jax

_pool_lock = threading.Lock()  # module-level lock in a worker module


def start_workers(n):
    ctx = multiprocessing.get_context("fork")  # fork after jax init
    return [ctx.Process(target=_worker, args=(i,)) for i in range(n)]


def _worker(i):
    return ShardedBatcher([], []).images
