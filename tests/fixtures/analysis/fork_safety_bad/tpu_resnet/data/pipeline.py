"""Host pipeline that imports jax at module scope — fine for the parent
process, fatal for the worker import closure it leaked into."""

import jax  # the violation the closure walk must surface
import numpy as np


class ShardedBatcher:
    def __init__(self, images, labels):
        self.images = np.asarray(images)
        self.labels = np.asarray(labels)

    def device_put(self):
        return jax.device_put(self.images)
