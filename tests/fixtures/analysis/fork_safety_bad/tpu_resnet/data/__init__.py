"""Pre-PR3 shape: an EAGER re-export in the worker's parent package —
drags the full pipeline (and through it jax) into every spawned decode
worker. The real tree resolves these lazily via PEP 562."""

from tpu_resnet.data.pipeline import ShardedBatcher  # noqa: F401
