"""Seeded blocking-under-lock fixture: queue get/put, event wait,
thread join, sleep and file/network I/O inside ``with lock:`` bodies —
every other acquirer of the lock waits on the blocked operation (the
PR 5 drain-hang shape)."""

import queue
import threading
import time
import urllib.request


class Stager:
    def __init__(self, it):
        self._it = it
        self._q = queue.Queue(maxsize=2)
        self._done = threading.Event()
        self._lock = threading.Lock()
        self.staged = 0
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self):
        for item in self._it:
            with self._lock:
                # BUG: blocking put while holding the stats lock — the
                # consumer needs the same lock to drain.
                self._q.put(item)
                self.staged += 1
        self._done.set()

    def take(self):
        with self._lock:
            # BUG: blocking get under the lock the producer needs.
            return self._q.get()

    def flush(self, path, url):
        with self._lock:
            # BUG: sleep / event wait / join / file / network under lock.
            time.sleep(0.5)
            self._done.wait()
            self._thread.join()
            with open(path, "w") as f:
                f.write(str(self.staged))
            urllib.request.urlopen(url)
