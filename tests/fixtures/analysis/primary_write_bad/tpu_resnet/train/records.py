"""Seeded primary-only-write fixture: a module writing the shared
train_dir artifacts directly instead of through the canonical atomic,
primary-only helpers (obs/manifest.write_manifest,
resilience/elastic.write_topology) — on a shared train_dir, N processes
race these writes into torn records."""

import json
import os


def note_topology(train_dir, mesh_shape):
    # BUG: bypasses elastic.write_topology (primary gate + tmp+replace).
    with open(os.path.join(train_dir, "topology.json"), "w") as f:
        json.dump({"mesh_shape": mesh_shape}, f)


def note_manifest(train_dir, cfg):
    # BUG: bypasses obs/manifest.write_manifest.
    path = os.path.join(train_dir, "manifest.json")
    with open(path, "w") as f:
        json.dump({"config": cfg}, f)
