"""Seeded signal-safety violations for the serve SIGTERM path: a handler
that drains the server inline instead of setting a flag for the main
loop. The real serve/server.py delegates to resilience.ShutdownCoordinator
(flag-only handler); this fixture is the anti-pattern that must stay
flagged if anyone ever 'simplifies' the drain into the handler."""

import signal
import time


class EagerDrainServer:
    """'Just drain right here in the handler' — every call below runs at
    an arbitrary bytecode boundary of the interrupted batcher loop."""

    def __init__(self, batcher, httpd, registry):
        self._batcher = batcher
        self._httpd = httpd
        self._registry = registry

    def install(self):
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        self._registry.mark_unhealthy("draining")  # fine: sets a flag
        self._drain_now(signum)                    # transitively unsafe

    def _drain_now(self, signum):
        self._batcher.drain(30.0)       # flagged: joins the worker thread
        time.sleep(0.1)                 # flagged: sleep in handler
        self._httpd.shutdown()          # flagged: socket teardown
        with open("/tmp/drained", "w") as fh:  # flagged: file I/O
            fh.write(str(signum))
