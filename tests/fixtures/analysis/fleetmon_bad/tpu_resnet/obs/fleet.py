"""PRE-FIX fleetmon scrape-ring race (seeded fixture — the bug shape
the real aggregator was written to avoid; the engine must flag it
mechanically).

The daemon scraper thread appends each round to ``self._rounds`` and
trims the ring with a bare rebind, while ``snapshot`` (called from the
telemetry handler thread) reads the list bare. A snapshot racing the
trim can read a half-rebound ring — or compute burn rate against a
round the trim just dropped. The fixed code
(tpu_resnet/obs/fleet.py) does the ring append/trim and every counter
bump under ``self._lock`` and keeps the scrape I/O outside it.
"""

import threading
import time


class FleetAggregator:
    def __init__(self, scrape_fn, interval=2.0):
        self._scrape = scrape_fn
        self._interval = interval
        self._rounds = []
        self._scrapes = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            merged = self._scrape()
            # BUG: bare ring append + trim-rebind from the scraper
            # thread while snapshot() reads the list unguarded.
            self._rounds.append({"wall": time.time(), "merged": merged})
            self._rounds = self._rounds[-4096:]
            self._scrapes = self._scrapes + 1
            self._stop.wait(self._interval)

    def snapshot(self):
        # BUG: unlocked read racing the scraper's trim-rebind.
        last = self._rounds[-1] if self._rounds else None
        return {"rounds": len(self._rounds),
                "scrapes": self._scrapes, "last": last}

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)
