"""The PRE-FIX constructor code (ADVICE r4 regression fixture).

This is the shape of models/resnet.py before the satellite fixes landed:
``BlockLayer`` silently drops ``bn_axis_name`` when fused, and the public
constructors carry none of ``build_model``'s guards — calling
``cifar_resnet_v2(28, 100, width_multiplier=10, fused_blocks=True)``
directly hits an obscure downstream tile error, and fused + sync-BN
silently computes per-replica BN. Rule guard-parity must flag all four
sites."""

from typing import Optional


class BlockLayer:
    filters: int = 16
    bottleneck: bool = False
    bn_axis_name: Optional[str] = None
    fused: bool = False

    def __call__(self, x, *, train: bool):
        # PRE-FIX: dispatches to the fused kernels without re-checking
        # bn_axis_name — sync-BN callers silently get per-replica BN.
        fuse = self.fused and not self.bottleneck
        block_cls = "FusedBuildingBlock" if fuse else "BuildingBlock"
        return block_cls, x, train


def cifar_resnet_v2(resnet_size, num_classes, width_multiplier=1,
                    bn_axis_name=None, fused_blocks=False):
    # PRE-FIX: no _check_fused_bn_axis, no width_multiplier guard.
    if resnet_size % 6 != 2:
        raise ValueError("resnet_size must be 6n+2")
    return ("ResNetV2", resnet_size, num_classes, width_multiplier,
            bn_axis_name, fused_blocks)


def imagenet_resnet_v2(resnet_size, num_classes, bn_axis_name=None,
                       fused_blocks=False):
    # PRE-FIX: no _check_fused_bn_axis.
    return ("ResNetV2", resnet_size, num_classes, bn_axis_name,
            fused_blocks)
