"""build_model KEEPS its guard in this fixture — the parity rule must
flag only the constructors that fail to mirror it."""

from tpu_resnet.models.resnet import cifar_resnet_v2


def build_model(cfg):
    if cfg.model.fused_blocks and cfg.model.width_multiplier > 1:
        raise ValueError("model.fused_blocks is only measured/tiled for "
                         "width_multiplier=1")
    return cifar_resnet_v2(cfg.model.resnet_size, cfg.data.num_classes,
                           width_multiplier=cfg.model.width_multiplier,
                           fused_blocks=cfg.model.fused_blocks)
