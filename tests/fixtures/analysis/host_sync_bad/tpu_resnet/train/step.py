"""Seeded jit-host-sync violations (fixture for tests/test_analysis.py).

This file sits at the jit-scope path (tpu_resnet/train/step.py) of a
fixture mini-tree: every hazard below must be flagged."""

import random
import time

import jax
import numpy as np


def make_train_step(model):
    def train_step(state, images, labels):
        print("step", state.step)                       # host I/O
        t0 = time.time()                                # host clock
        noise = np.random.default_rng(0).normal()       # trace-time RNG
        jitter = random.random()                        # trace-time RNG
        loss = (images.mean() + noise + jitter).item()  # device sync
        host_labels = jax.device_get(labels)            # device sync
        images.block_until_ready()                      # device sync
        return state, {"loss": loss, "t": t0,
                       "labels": host_labels}

    return train_step


def clean_helper(images):
    # No hazards: must NOT be flagged.
    return images.astype("float32") / 255.0
