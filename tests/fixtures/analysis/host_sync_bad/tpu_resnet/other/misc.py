"""A @jax.jit function OUTSIDE the jit-scope modules: the rule must
still find it via its decorator; the undecorated sibling is exempt."""

import jax


@jax.jit
def jitted_probe(x):
    print("inside jit")   # flagged: host I/O under an explicit jax.jit
    return x * 2


def host_side_logger(x):
    print("host", x)      # NOT flagged: plain host function
    return x
