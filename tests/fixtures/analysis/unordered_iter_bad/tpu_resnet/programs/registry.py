"""Seeded unordered-iteration fixture: set order and directory-scan
order feeding program construction / key spelling — two processes can
enumerate these differently (PYTHONHASHSEED, filesystem) and build or
name programs in diverging orders."""

import glob
import os

import jax


def warm_buckets(fn, buckets):
    programs = {}
    # BUG: set order varies across processes.
    for b in set(buckets):
        programs[b] = jax.jit(fn)
    return programs


def spell_all(entries):
    # BUG: set comprehension feeding the key spelling.
    return [f"train|{name}" for name in {e.name for e in entries}]


def cache_entries(cache_dir):
    # BUG: glob order is filesystem-dependent.
    return [os.path.basename(p)
            for p in glob.glob(os.path.join(cache_dir, "*.bin"))]
