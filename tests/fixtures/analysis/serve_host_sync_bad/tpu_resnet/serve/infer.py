"""Seeded jit-host-sync violations in the serving hot path: host work
inside the compiled inference fn runs per coalesced batch and multiplies
into every request's latency (the real serve/infer.py is jit scope)."""

import time

import numpy as np


def make_serve_infer(model):
    def infer(variables, images):
        t0 = time.perf_counter()          # flagged: host clock under jit
        print("serving batch", images.shape)   # flagged: host I/O
        noise = np.random.uniform(size=images.shape)  # flagged: host RNG
        logits = model.apply(variables, images + noise, train=False)
        logits.block_until_ready()        # flagged: device sync per call
        _ = time.perf_counter() - t0
        return logits

    return infer


def clean_helper(stats):
    # Hazard-free function in the same jit-scope file: must stay silent.
    return dict(stats)
