"""PRE-FIX PR 11 swap lock (seeded fixture).

The hot-reload restore thread swaps ``_variables`` bare while
``maybe_reload`` swaps it under the lock (the guard discipline drifted
between the two sites), and ``close()`` tears the checkpoint manager
down while the daemon restore thread may still be mid-restore — the
drain-during-reload window the real backend closes with ``_swap_lock``
held on BOTH sides.
"""

import threading


class CheckpointBackend:
    def __init__(self, ckpt, template):
        self._ckpt = ckpt
        self._template = template
        self._swap_lock = threading.Lock()
        self._variables = None
        self._closed = False
        self._restore_thread = threading.Thread(
            target=self._load, args=(0,), daemon=True)
        self._restore_thread.start()

    def _load(self, step):
        state = self._ckpt.restore(self._template, step)
        # BUG: the restore thread publishes the swap bare while
        # maybe_reload's path publishes under the swap lock.
        self._variables = {"params": state.params}

    def maybe_reload(self, step):
        with self._swap_lock:
            state = self._ckpt.restore(self._template, step)
            self._variables = {"params": state.params}

    def infer(self, images):
        return self._variables, images

    def close(self):
        # BUG: frees the manager the daemon restore thread is using —
        # no join, no stop event, no common lock (the real close() holds
        # _swap_lock, and _load aborts under it when closed).
        self._closed = True
        self._ckpt.release()
