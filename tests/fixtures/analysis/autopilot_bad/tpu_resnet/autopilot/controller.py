"""Seeded host-isolation violation for the autopilot control plane.

The real autopilot/controller.py is stdlib-only: the autoscaler must
keep steering (and drain-scaling the fleet) on a host whose
accelerator stack is the thing that is melting — a module-scope jax
import would take the control loop down with the data plane. This
fixture is the anti-pattern that must stay flagged.
"""

import threading
import time

import jax  # host-isolation: the autopilot must never import jax


class EagerController:
    """'Just read the device gauges directly' — couples every control
    round to a working accelerator runtime."""

    def __init__(self, poll_interval=1.0):
        self._interval = poll_interval
        self._stop = threading.Event()

    def run_round(self):
        free = jax.devices()[0].memory_stats()["bytes_available"]
        return {"wall": time.time(), "hbm_free": free}

    def loop(self):
        while not self._stop.is_set():
            self.run_round()
            self._stop.wait(self._interval)
