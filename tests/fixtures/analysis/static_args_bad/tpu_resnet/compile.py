"""Seeded jit-static-args violations (fixture for test_analysis.py)."""

import jax


@jax.jit
def decorated_step(x, train: bool):         # flagged: traced bool param
    return x if x.sum() > 0 else -x


@jax.jit
def defaulted_mode(x, mode="fast"):         # flagged: traced str default
    return x


@jax.jit
def covered_ok(x, eps: float = 1e-5):       # NOT flagged: float traces fine
    return x + eps


def helper(x, train: bool):
    return x


helper_jitted = jax.jit(helper, static_argnums=(1,))   # NOT flagged: covered
helper_named = jax.jit(helper, static_argnames=("train",))  # NOT flagged
helper_bad = jax.jit(helper)                # flagged: bool param uncovered
lambda_bad = jax.jit(lambda x, flag=True: x)  # flagged: bool default
wrong_container = jax.jit(helper, static_argnums={1})  # flagged: unhashable
wrong_kind = jax.jit(helper, static_argnums=("train",))  # flagged: str argnum

IDX = 1
symbolic_ok = jax.jit(helper, static_argnums=(0, IDX))  # NOT flagged:
# symbolic element — coverage unknowable, legal jax; sub-check B skipped


def posonly(x, /, train: bool):
    return x


posonly_ok = jax.jit(posonly, static_argnums=(1,))  # NOT flagged: index 1
# counts posonlyargs + args together, exactly as jax does


@jax.jit
def kwonly_bad(x, *, train: bool = True):  # flagged: kw-only traced bool
    return x

