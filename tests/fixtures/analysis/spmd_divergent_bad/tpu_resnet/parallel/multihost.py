"""Seeded SPMD-divergence fixture — the classic pod deadlock shapes,
planted in the module the multi-host on-ramp owns (parallel/multihost
joins the lint scope; ROADMAP item 1).

Every gated call here runs on SOME processes only: the others never
enter the collective / never build the program, and the pod hangs at
the next synchronization point instead of raising anywhere.
"""

import jax

from tpu_resnet.programs import registry
from tpu_resnet.train.step import make_train_step


def is_primary():
    return jax.process_index() == 0


def build_programs(fn, avals, state):
    if jax.process_index() == 0:
        # BUG: only process 0 compiles — everyone else diverges at the
        # first dispatch.
        step = jax.jit(fn)
    else:
        step = fn
    if is_primary():
        # BUG: registry dispatch gated on primary.
        program, _ = registry.wrap("train", fn, avals)
        step_fn = make_train_step(fn, avals)
        _ = (program, step_fn)
    return step(state)


def sync_metrics(metrics, process_id):
    if process_id == 0:
        # BUG: a collective only the primary enters — all-host hang.
        return jax.lax.psum(metrics, "data")
    return metrics
