"""Seeded host-isolation violation for the scenario conductor. The real
scenario package is jax-free at module scope by contract — it drills
hosts whose accelerator stack is the thing under test, and only its
CHILD processes may touch jax. This fixture is the anti-pattern that
must stay flagged: a module-scope jax import in the conductor."""

import os

import jax  # host-isolation: the conductor must never import jax


def conduct(data, run_dir):
    return {"scenario": data.get("name"), "run_dir": run_dir,
            "devices": jax.device_count(), "pid": os.getpid()}
