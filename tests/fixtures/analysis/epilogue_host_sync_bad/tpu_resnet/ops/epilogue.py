"""Seeded jit-host-sync violations in the fused-epilogue kernel module:
ops/* is jit scope — the epilogue wrappers trace into every train step
that enables them, so a host clock or RNG here runs once at trace time
and bakes garbage (or a sync) into the compiled program."""

import random
import time

import jax


def scale_bias_relu_auto(x, scale, bias):
    t0 = time.monotonic()                 # flagged: host clock under jit
    if random.random() < 0.5:             # flagged: host RNG at trace
        scale = scale * 1.0
    y = jax.numpy.maximum(x * scale + bias, 0.0)
    host = jax.device_get(y)              # flagged: device->host transfer
    print("epilogue took", time.monotonic() - t0, host.shape)  # flagged
    return y


def clean_fold(gamma, beta, mean, var, eps):
    # Hazard-free function in the same jit-scope file: must stay silent.
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale
