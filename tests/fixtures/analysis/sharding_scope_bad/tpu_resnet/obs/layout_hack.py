"""Seeded sharding-scope violation: a helper constructing NamedSharding
and pinning layouts with with_sharding_constraint outside the
partitioner-owned modules — the bypass pattern the sharding-scope lint
exists to catch (a sharding injected here changes the compiled
program's collective structure behind the golden comms ledgers' back)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def sneaky_shard(mesh, tree):
    # NamedSharding construction outside the partitioner scope
    sharding = NamedSharding(mesh, P("data"))
    return jax.device_put(tree, sharding)


def sneaky_constraint(mesh, grads):
    # with_sharding_constraint outside the partitioner scope
    return jax.lax.with_sharding_constraint(
        grads, NamedSharding(mesh, P(None, "data")))
