"""Seeded jit-host-sync violations in the sweep harness's jit-program
assembly: tools/sweep_measure.py is jit scope — the programs built here
are exactly what a sweep point measures, so a host sync baked in here
would corrupt every knob's number (the timing loop belongs in sweep.py,
the host side)."""

import time

import numpy as np


def build_point_programs(cfg, mesh, donate_state=True):
    t0 = time.perf_counter()              # flagged: host clock
    seed = np.random.randint(0, 2 ** 31)  # flagged: host RNG at trace
    state = {"seed": seed}

    def step_fn(state, images, labels):
        loss = (images.sum() + labels.sum()).item()  # flagged: .item()
        print("step loss", loss)          # flagged: host I/O
        return state, {"loss": loss}

    _ = time.perf_counter() - t0
    return state, step_fn, None


def clean_space(space):
    # Hazard-free function in the same jit-scope file: must stay silent.
    return sorted(space)
