#!/usr/bin/env python3
"""Restart supervisor for preemptible training jobs.

The trainer's graceful-shutdown path (tpu_resnet/resilience/shutdown.py)
turns SIGTERM/SIGINT into: finish the chunk, save a final checkpoint,
exit with a distinct code (default 42). This wrapper closes the loop — it
reruns the command so the run resumes from that checkpoint, with two
different policies by exit code:

- **preempt code** (machine reclaimed, clean save on disk): restart after
  a short fixed delay; these are expected and don't count against the
  crash backoff.
- **any other nonzero code** (real crash): restart with capped,
  decorrelated-jitter exponential backoff (each delay drawn uniformly
  from [base, 3 · previous], capped at --backoff-cap) so a fleet that
  shares a fault doesn't stampede the cluster in synchronized restart
  waves; the crash streak resets on any clean interval. The chosen
  delay is logged.
- **0**: done, exit 0.

**Downsize policy** (elastic capacity, resilience/elastic.py): with
``--downsize-after N --mesh-ladder 4,2``, N preemptions inside
``--downsize-window`` seconds mean this host's capacity is churning —
instead of resuming at the same shape and being reclaimed again, the
next restart appends ``mesh.data=<rung>`` (the next ladder entry) to the
command, and the trainer's elastic resume reshards the checkpoint onto
the smaller mesh. Later CLI overrides win in the config system, so the
appended override takes effect without editing the base command.

**Fleet mode** (serving): ``--fleet N`` supervises N children of one
command template from a single invocation — ``{i}`` in the args becomes
the child index, so ``serve.replica_name=r{i}`` names each replica's
discovery file. Every child keeps its own independent decorrelated-
jitter backoff (a fleet sharing a fault must not stampede back in
lockstep); ``--stop-codes 3`` honors the serve colocation-admission
verdict (exit 3 = "no capacity on this host" — restarting here is
pointless; let the placement layer pick another host); and
``--restart-clean-exits`` gives exit 0 fleet semantics — a replica that
exits 0 was *drained* (``route --drain``, rolling upgrade) and must come
back so the router readmits it, unlike a trainer whose 0 means "done".

Usage:

    python tools/supervise.py [options] -- python -m tpu_resnet train \
        --preset cifar10 train.train_dir=/data/run1

    python tools/supervise.py --fleet 2 --stop-codes 3 \
        --restart-clean-exits -- \
        python -m tpu_resnet serve --preset cifar10 \
        train.train_dir=/data/run1 serve.replica_name=r{i}

Stdlib-only and jax-free: it must keep working on a host whose accelerator
stack is the thing that is crashing.
"""

from __future__ import annotations

import argparse
import logging
import random
import subprocess
import sys
import time

log = logging.getLogger("tpu_resnet.supervise")

# Canonical values live in tpu_resnet/resilience/exitcodes.py; the
# fallback keeps the supervisor runnable on a host without the package
# installed (its core contract — it babysits the thing that crashes).
try:
    from tpu_resnet.resilience import exitcodes as _exitcodes

    DEFAULT_PREEMPT_CODE = _exitcodes.PREEMPTED
except ImportError:  # standalone copy of this file, package absent
    DEFAULT_PREEMPT_CODE = 42


def _run_id_of(cmd) -> str:
    """Best-effort run_id of the supervised trainer: find the
    ``train.train_dir=...`` override in ``cmd`` and read the run_id.json
    the trainer minted there (obs/manifest.py). Stdlib-only; '' when
    unknown. Logged with every restart so a supervisor log line can be
    joined to the run's trace-export timeline."""
    import json
    import os

    train_dir = None
    for arg in cmd:
        if isinstance(arg, str) and arg.startswith("train.train_dir="):
            train_dir = arg.split("=", 1)[1]
    if not train_dir:
        return ""
    try:
        with open(os.path.join(train_dir, "run_id.json")) as f:
            return str(json.load(f).get("run_id") or "")
    except (OSError, ValueError):
        return ""


class DownsizePolicy:
    """Restart with a smaller mesh after repeated preemptions.

    ``threshold`` preemptions inside ``window_sec`` pop the next rung of
    ``ladder`` (data-axis sizes, largest first, e.g. ``(4, 2)``) — the
    signal that this host's capacity is churning and the run should ride
    the wave at a smaller shape instead of thrashing at the original
    one. The preemption history clears on each downsize (the new shape
    gets a fresh window) and on any crash-free completion. ``clock`` is
    injectable for tests."""

    def __init__(self, threshold: int, window_sec: float, ladder,
                 clock=time.time):
        self.threshold = int(threshold)
        self.window_sec = float(window_sec)
        self.ladder = [int(x) for x in ladder]
        self.clock = clock
        self.events = []  # preemption timestamps inside the window

    def note_preempt(self):
        """Record one preemption; returns the new ``mesh.data`` size when
        the policy triggers, else None."""
        if self.threshold <= 0:
            return None
        now = self.clock()
        self.events.append(now)
        self.events = [t for t in self.events
                       if now - t <= self.window_sec]
        if len(self.events) >= self.threshold and self.ladder:
            self.events.clear()
            return self.ladder.pop(0)
        return None


def supervise(cmd, max_restarts: int = 100, preempt_code: int =
              DEFAULT_PREEMPT_CODE, backoff_base: float = 1.0,
              backoff_cap: float = 300.0, preempt_delay: float = 1.0,
              jitter: bool = True, rng=None,
              downsize_after: int = 0, downsize_window: float = 600.0,
              mesh_ladder=(), stop_codes=(), restart_clean: bool = False,
              run=None, sleep=time.sleep) -> int:
    """Run ``cmd`` under the restart policy; returns the final exit code.
    ``run``/``sleep``/``rng`` are injectable for tests; ``jitter=False``
    restores the deterministic base·2^crashes schedule. ``stop_codes``
    are exit codes that END supervision immediately (no restart) while
    still reporting the code — the serve fleet uses 3 here, the
    colocation-admission "placed elsewhere" verdict (resilience/
    elastic.py): restarting on the same host would just be denied
    again. ``restart_clean=True`` restarts exit-0 children too (after
    ``preempt_delay``, no crash backoff): serving-fleet semantics, where
    a replica's clean exit means it was DRAINED for a rolling
    hot-reload/upgrade (``route --drain``) and the upgrade contract is
    that it comes back and the router readmits it — without this the
    documented rolling drain would permanently shrink the fleet."""
    if run is None:
        run = lambda c: subprocess.call(c)  # noqa: E731
    if rng is None:
        rng = random.Random()
    stop_codes = set(stop_codes)
    policy = (DownsizePolicy(downsize_after, downsize_window, mesh_ladder)
              if downsize_after > 0 and mesh_ladder else None)
    mesh_override = None  # appended last: later config overrides win
    restarts = 0
    crash_streak = 0
    prev_delay = backoff_base
    while True:
        rc = run(list(cmd) + ([mesh_override] if mesh_override else []))
        run_id = _run_id_of(cmd)
        if run_id:
            log.info("supervised run_id=%s exited %d", run_id, rc)
        if rc == 0 and not restart_clean:
            log.info("command exited 0 after %d restart(s)", restarts)
            return 0
        if rc in stop_codes:
            log.warning("exit code %d is a stop code (e.g. colocation "
                        "admission denied) — not restarting", rc)
            return rc
        if restarts >= max_restarts:
            log.error("giving up after %d restart(s); last exit code %d",
                      restarts, rc)
            return rc
        restarts += 1
        if rc == 0:
            # Clean exit under restart_clean = a drained serve replica
            # in a rolling upgrade: bring it straight back (preempt-
            # style fixed delay, no crash backoff) so the router's
            # probe readmits it and the fleet regains capacity.
            crash_streak = 0
            prev_delay = backoff_base
            delay = preempt_delay
            log.info("clean exit (drained) — restarting in %.1fs for "
                     "the rolling-upgrade readmit (restart %d/%d)",
                     delay, restarts, max_restarts)
        elif rc == preempt_code:
            crash_streak = 0
            prev_delay = backoff_base
            delay = preempt_delay
            rung = policy.note_preempt() if policy is not None else None
            if rung is not None:
                mesh_override = f"mesh.data={rung}"
                log.warning(
                    "downsize policy: %d preemption(s) within %.0fs — "
                    "restarting with %s (elastic resume reshards the "
                    "checkpoint onto the smaller mesh)",
                    downsize_after, downsize_window, mesh_override)
            log.warning("preempted (exit %d) — resuming from the final "
                        "checkpoint in %.1fs (restart %d/%d)%s", rc, delay,
                        restarts, max_restarts,
                        f" with {mesh_override}" if mesh_override else "")
        else:
            crash_streak += 1
            if jitter:
                # Decorrelated jitter: uniform in [base, 3·previous],
                # capped — a fleet restarting after a shared fault
                # spreads out instead of stampeding in lockstep.
                delay = min(backoff_cap,
                            rng.uniform(backoff_base,
                                        max(backoff_base, prev_delay) * 3))
            else:
                delay = min(backoff_cap,
                            backoff_base * (2 ** (crash_streak - 1)))
            prev_delay = delay
            log.warning("crashed (exit %d) — restart %d/%d in %.1fs "
                        "(crash streak %d%s)", rc, restarts, max_restarts,
                        delay, crash_streak,
                        ", decorrelated jitter" if jitter else "")
        sleep(delay)


def supervise_fleet(cmd, fleet: int, placeholder: str = "{i}",
                    **kwargs) -> int:
    """Fleet mode: supervise ``fleet`` children of ``cmd`` from ONE
    invocation, each under its own independent restart policy (the
    decorrelated-jitter crash backoff per child is exactly what keeps a
    fleet that shares a fault from restarting in stampede lockstep).

    ``placeholder`` occurrences in the command args are substituted with
    the child index, so one template names per-replica identities:

        supervise.py --fleet 3 -- python -m tpu_resnet serve \\
            train.train_dir=/data/run1 serve.replica_name=r{i}

    Returns 0 when every child ends 0, else the first nonzero child
    code. Stdlib-only, one thread per child (the children are processes;
    the threads just run their restart loops)."""
    import threading

    rcs = [None] * fleet
    threads = []
    for i in range(fleet):
        child_cmd = [a.replace(placeholder, str(i))
                     if isinstance(a, str) else a for a in cmd]

        def runner(idx=i, c=child_cmd):
            log.info("fleet child %d: %s", idx, " ".join(map(str, c)))
            rcs[idx] = supervise(c, **kwargs)
            log.info("fleet child %d finished rc=%s", idx, rcs[idx])

        t = threading.Thread(target=runner, name=f"supervise-fleet-{i}",
                             daemon=True)
        threads.append(t)
        t.start()
    for t in threads:
        t.join()
    bad = [rc for rc in rcs if rc not in (0, None)]
    log.info("fleet done: rcs=%s", rcs)
    return bad[0] if bad else 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
        datefmt="%H:%M:%S", stream=sys.stderr)
    p = argparse.ArgumentParser(
        description="restart wrapper: auto-resume on the trainer's "
                    "preemption exit code, capped exponential backoff on "
                    "crashes")
    p.add_argument("--max-restarts", type=int, default=100)
    p.add_argument("--preempt-code", type=int, default=DEFAULT_PREEMPT_CODE,
                   help="exit code meaning 'preempted, resume me' "
                        "(resilience.preempt_exit_code)")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="first crash-restart delay, seconds")
    p.add_argument("--backoff-cap", type=float, default=300.0,
                   help="max crash-restart delay, seconds")
    p.add_argument("--preempt-delay", type=float, default=1.0,
                   help="fixed delay before resuming after a preemption")
    p.add_argument("--no-jitter", action="store_true",
                   help="disable the decorrelated crash-backoff jitter "
                        "(deterministic base*2^crashes schedule)")
    p.add_argument("--downsize-after", type=int, default=0,
                   help="preemptions inside --downsize-window that "
                        "trigger a mesh downsize (0 = policy off)")
    p.add_argument("--downsize-window", type=float, default=600.0,
                   help="downsize-policy window, seconds")
    p.add_argument("--mesh-ladder", default="",
                   help="comma-separated mesh.data sizes to step down "
                        "through on downsize, largest first (e.g. 4,2)")
    p.add_argument("--fleet", type=int, default=0,
                   help="fleet mode: supervise N children of the same "
                        "command template, '{i}' in args replaced by "
                        "the child index (serve.replica_name=r{i}); "
                        "each child keeps its own restart policy")
    p.add_argument("--stop-codes", default="",
                   help="comma-separated exit codes that stop "
                        "supervision without a restart (e.g. 3 = serve "
                        "colocation admission denied: this host has no "
                        "capacity, restarting here is pointless)")
    p.add_argument("--restart-clean-exits", action="store_true",
                   help="restart exit-0 children too (serving fleets: a "
                        "replica's clean exit means it was DRAINED for "
                        "a rolling upgrade and must come back for the "
                        "router to readmit; trainers keep the default "
                        "'0 = done')")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to supervise (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no command given; usage: supervise.py [options] -- cmd ...")
    try:
        ladder = tuple(int(x) for x in args.mesh_ladder.split(",")
                       if x.strip())
    except ValueError:
        p.error(f"--mesh-ladder must be comma-separated integers "
                f"(e.g. 4,2): {args.mesh_ladder!r}")
    try:
        stop_codes = tuple(int(x) for x in args.stop_codes.split(",")
                           if x.strip())
    except ValueError:
        p.error(f"--stop-codes must be comma-separated integers: "
                f"{args.stop_codes!r}")
    if args.downsize_after > 0 and not ladder:
        p.error("--downsize-after requires --mesh-ladder")
    kwargs = dict(max_restarts=args.max_restarts,
                  preempt_code=args.preempt_code,
                  backoff_base=args.backoff_base,
                  backoff_cap=args.backoff_cap,
                  preempt_delay=args.preempt_delay,
                  jitter=not args.no_jitter,
                  downsize_after=args.downsize_after,
                  downsize_window=args.downsize_window,
                  mesh_ladder=ladder, stop_codes=stop_codes,
                  restart_clean=args.restart_clean_exits)
    if args.fleet > 0:
        return supervise_fleet(cmd, args.fleet, **kwargs)
    return supervise(cmd, **kwargs)


if __name__ == "__main__":
    sys.exit(main())
