#!/usr/bin/env python3
"""Restart supervisor for preemptible training jobs.

The trainer's graceful-shutdown path (tpu_resnet/resilience/shutdown.py)
turns SIGTERM/SIGINT into: finish the chunk, save a final checkpoint,
exit with a distinct code (default 42). This wrapper closes the loop — it
reruns the command so the run resumes from that checkpoint, with two
different policies by exit code:

- **preempt code** (machine reclaimed, clean save on disk): restart after
  a short fixed delay; these are expected and don't count against the
  crash backoff.
- **any other nonzero code** (real crash): restart with capped
  exponential backoff (base · 2^crashes, up to --backoff-cap) so a
  hard-broken job can't hot-loop the cluster; the crash streak resets on
  any clean interval.
- **0**: done, exit 0.

Usage:

    python tools/supervise.py [options] -- python -m tpu_resnet train \
        --preset cifar10 train.train_dir=/data/run1

Stdlib-only and jax-free: it must keep working on a host whose accelerator
stack is the thing that is crashing.
"""

from __future__ import annotations

import argparse
import logging
import subprocess
import sys
import time

log = logging.getLogger("tpu_resnet.supervise")

# Keep in sync with tpu_resnet/resilience/shutdown.py PREEMPT_EXIT_CODE
# (not imported: the supervisor must run without the package installed).
DEFAULT_PREEMPT_CODE = 42


def _run_id_of(cmd) -> str:
    """Best-effort run_id of the supervised trainer: find the
    ``train.train_dir=...`` override in ``cmd`` and read the run_id.json
    the trainer minted there (obs/manifest.py). Stdlib-only; '' when
    unknown. Logged with every restart so a supervisor log line can be
    joined to the run's trace-export timeline."""
    import json
    import os

    train_dir = None
    for arg in cmd:
        if isinstance(arg, str) and arg.startswith("train.train_dir="):
            train_dir = arg.split("=", 1)[1]
    if not train_dir:
        return ""
    try:
        with open(os.path.join(train_dir, "run_id.json")) as f:
            return str(json.load(f).get("run_id") or "")
    except (OSError, ValueError):
        return ""


def supervise(cmd, max_restarts: int = 100, preempt_code: int =
              DEFAULT_PREEMPT_CODE, backoff_base: float = 1.0,
              backoff_cap: float = 300.0, preempt_delay: float = 1.0,
              run=None, sleep=time.sleep) -> int:
    """Run ``cmd`` under the restart policy; returns the final exit code.
    ``run``/``sleep`` are injectable for tests."""
    if run is None:
        run = lambda c: subprocess.call(c)  # noqa: E731
    restarts = 0
    crash_streak = 0
    while True:
        rc = run(cmd)
        run_id = _run_id_of(cmd)
        if run_id:
            log.info("supervised run_id=%s exited %d", run_id, rc)
        if rc == 0:
            log.info("command exited 0 after %d restart(s)", restarts)
            return 0
        if restarts >= max_restarts:
            log.error("giving up after %d restart(s); last exit code %d",
                      restarts, rc)
            return rc
        restarts += 1
        if rc == preempt_code:
            crash_streak = 0
            delay = preempt_delay
            log.warning("preempted (exit %d) — resuming from the final "
                        "checkpoint in %.1fs (restart %d/%d)", rc, delay,
                        restarts, max_restarts)
        else:
            crash_streak += 1
            delay = min(backoff_cap,
                        backoff_base * (2 ** (crash_streak - 1)))
            log.warning("crashed (exit %d) — restart %d/%d in %.1fs "
                        "(crash streak %d)", rc, restarts, max_restarts,
                        delay, crash_streak)
        sleep(delay)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
        datefmt="%H:%M:%S", stream=sys.stderr)
    p = argparse.ArgumentParser(
        description="restart wrapper: auto-resume on the trainer's "
                    "preemption exit code, capped exponential backoff on "
                    "crashes")
    p.add_argument("--max-restarts", type=int, default=100)
    p.add_argument("--preempt-code", type=int, default=DEFAULT_PREEMPT_CODE,
                   help="exit code meaning 'preempted, resume me' "
                        "(resilience.preempt_exit_code)")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="first crash-restart delay, seconds")
    p.add_argument("--backoff-cap", type=float, default=300.0,
                   help="max crash-restart delay, seconds")
    p.add_argument("--preempt-delay", type=float, default=1.0,
                   help="fixed delay before resuming after a preemption")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to supervise (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no command given; usage: supervise.py [options] -- cmd ...")
    return supervise(cmd, max_restarts=args.max_restarts,
                     preempt_code=args.preempt_code,
                     backoff_base=args.backoff_base,
                     backoff_cap=args.backoff_cap,
                     preempt_delay=args.preempt_delay)


if __name__ == "__main__":
    sys.exit(main())
