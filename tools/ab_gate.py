"""Shared win/loss rule for the gated fused-kernel battery stages.

One place owns "did the A/B show a winning direction" so the gate in
stage 55, the loss-detector and gate in stage 56, and the summary's
verdict (tools/battery_summary.py) cannot desynchronize (review finding
r5: the 6-line by_shape/speedup>1 computation was copy-pasted four
times).

Exit codes, matching the stages' historical contract:
    0  at least one measured direction has speedup > WIN_THRESHOLD
    1  measured loss — no direction wins (a standing negative result)
    2  artifact unreadable / no measured directions (infra error: the
       battery retries instead of recording a crash as a loss)

Usage: ``python tools/ab_gate.py ARTIFACT.json``
"""

import json
import sys

WIN_THRESHOLD = 1.0


def wins(artifact: dict):
    """Per-direction win booleans across all shapes of an A/B artifact."""
    return [d.get("speedup", 0) > WIN_THRESHOLD
            for shape in artifact.get("by_shape", {}).values()
            for d in shape.values() if isinstance(d, dict)]


def main(argv):
    try:
        with open(argv[1]) as f:
            r = json.load(f)
    except Exception as e:  # torn/invalid artifact: infra error, not a loss
        print(f"[ab_gate] artifact unreadable: {e}")
        return 2
    # A compile-smoke failure artifact (tools/pallas_compile_smoke.py,
    # archived in place of the A/B by stages 05/55) is a measured
    # infeasibility: the kernel cannot even lower on this chip, so the
    # gated stages must stand down exactly as on a measured loss.
    if r.get("compile_ok") is False:
        print("[ab_gate] compile smoke failed — kernel infeasible on this "
              "backend (standing loss)")
        return 1
    w = wins(r)
    if not w:
        print("[ab_gate] artifact has no measured directions")
        return 2
    return 0 if any(w) else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
