"""Compile-smoke prelude for the fused-kernel battery stages (VERDICT r4
item 3): one tiny NON-INTERPRET Pallas compile+run per kernel direction
on the live chip, before the 1800 s A/B commits the window.

Rationale: both fused families are oracle-tested in interpret mode only
(on CPU, Pallas lowers to ordinary XLA ops), so the first live window is
the kernels' first real Mosaic compile — a lowering error or VMEM-plan
miscalculation inside the A/B would burn the decisive window. This
prelude fails in ~a minute instead, writing the error as an artifact the
gates (tools/ab_gate.py) read as a measured infeasibility, and the
battery falls through to the headline bench.

    python tools/pallas_compile_smoke.py --family block --out s.json
    python tools/pallas_compile_smoke.py --family bottleneck --out s.json

Exit codes: 0 = all directions compiled and matched the oracle;
1 = a compile/runtime/accuracy failure (captured in --out). A hang is
the caller's ``timeout`` to kill (stage treats 124 as tunnel flake →
retry, not infeasibility).

``--interpret`` forces interpret mode so the harness itself is testable
on CPU (tests/test_compile_smoke.py); without it the kernels compile for
the ambient backend — the entire point on a live chip.
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TOL = 2e-2   # bf16-accumulation-friendly oracle tolerance


def _err(a, b):
    import numpy as np
    return float(np.max(np.abs(np.asarray(a, dtype="float32")
                               - np.asarray(b, dtype="float32"))))


def _smoke_block(interpret):
    """Tiny basic-block shapes: fwd, custom-VJP bwd, train fwd+bwd."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet.ops import fused_block as fb

    k = jax.random.PRNGKey(0)
    b, h, c = 8, 8, 32
    ks = jax.random.split(k, 8)
    x = jax.random.normal(ks[0], (b, h, h, c), jnp.float32)
    w1 = jax.random.normal(ks[1], (3, 3, c, c), jnp.float32) * 0.1
    w2 = jax.random.normal(ks[2], (3, 3, c, c), jnp.float32) * 0.1
    s1, b1 = jnp.ones((c,)), jnp.zeros((c,))
    s2, b2 = jnp.ones((c,)) * 0.5, jnp.zeros((c,)) + 0.1
    g1, be1 = jnp.ones((c,)), jnp.zeros((c,))
    g2, be2 = jnp.ones((c,)), jnp.zeros((c,))
    checks = {}

    y = fb.block_fwd(x, w1, w2, s1, b1, s2, b2, batch_tile=b,
                     interpret=interpret)
    y_ref = fb.block_fwd_reference(x, w1, w2, s1, b1, s2, b2)
    checks["fwd_max_err"] = _err(y, y_ref)

    def loss(args, f):
        return jnp.sum(f(*args) ** 2)

    args = (x, w1, w2, s1, b1, s2, b2)
    g = jax.grad(lambda a: loss(
        a, lambda *t: fb.block_apply(*t, batch_tile=b,
                                     interpret=interpret)))(args)
    g_ref = jax.grad(lambda a: loss(a, fb.block_fwd_reference))(args)
    checks["bwd_max_err"] = max(_err(gi, ri) for gi, ri in zip(g, g_ref))

    targs = (x, w1, w2, g1, be1, g2, be2)
    yt, moments = fb.block_train_apply(*targs, batch_tile=b,
                                       interpret=interpret)
    yt_ref, _ = fb.block_train_fwd_reference(*targs)
    checks["train_fwd_max_err"] = _err(yt, yt_ref)
    gt = jax.grad(lambda a: jnp.sum(
        fb.block_train_apply(*a, batch_tile=b,
                             interpret=interpret)[0] ** 2))(targs)
    gt_ref = jax.grad(lambda a: jnp.sum(
        fb.block_train_fwd_reference(*a)[0] ** 2))(targs)
    checks["train_bwd_max_err"] = max(
        _err(gi, ri) for gi, ri in zip(gt, gt_ref))
    return checks


def _smoke_bottleneck(interpret):
    """Tiny halo-tiled bottleneck at f=64 geometry: fwd + custom-VJP bwd."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet.ops import fused_bottleneck as fbn

    k = jax.random.PRNGKey(1)
    b, h, f = 1, 14, 64
    c4 = 4 * f
    ks = jax.random.split(k, 4)
    x = jax.random.normal(ks[0], (b, h, h, c4), jnp.float32)
    w1 = jax.random.normal(ks[1], (c4, f), jnp.float32) * 0.05
    w2 = jax.random.normal(ks[2], (3, 3, f, f), jnp.float32) * 0.05
    w3 = jax.random.normal(ks[3], (f, c4), jnp.float32) * 0.05
    s1, b1 = jnp.ones((c4,)), jnp.zeros((c4,))
    s2, b2 = jnp.ones((f,)) * 0.5, jnp.zeros((f,))
    s3, b3 = jnp.ones((f,)), jnp.zeros((f,)) + 0.1
    args = (x, w1, w2, w3, s1, b1, s2, b2, s3, b3)
    checks = {}

    y = fbn.bottleneck_fwd(*args, batch_tile=1, row_tile=h,
                           interpret=interpret)
    y_ref = fbn.bottleneck_fwd_reference(*args)
    checks["fwd_max_err"] = _err(y, y_ref)

    g = jax.grad(lambda a: jnp.sum(fbn.bottleneck_apply(
        *a, batch_tile=1, row_tile=h, interpret=interpret) ** 2))(args)
    g_ref = jax.grad(lambda a: jnp.sum(
        fbn.bottleneck_fwd_reference(*a) ** 2))(args)
    checks["bwd_max_err"] = max(_err(gi, ri) for gi, ri in zip(g, g_ref))
    return checks


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=("block", "bottleneck"),
                    required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--interpret", action="store_true",
                    help="force interpret mode (CPU harness test)")
    ns = ap.parse_args(argv)

    t0 = time.time()
    art = {"family": ns.family, "interpret": bool(ns.interpret)}
    interpret = True if ns.interpret else False
    try:
        import jax
        art["backend"] = jax.default_backend()
        checks = (_smoke_block if ns.family == "block"
                  else _smoke_bottleneck)(interpret)
        art["checks"] = {k: round(v, 6) for k, v in checks.items()}
        worst = max(checks.values())
        art["compile_ok"] = worst < _TOL
        if not art["compile_ok"]:
            art["error"] = f"oracle mismatch: max_err={worst:.4g} > {_TOL}"
    except Exception:
        art["compile_ok"] = False
        art["error"] = traceback.format_exc()[-2000:]
    art["elapsed_s"] = round(time.time() - t0, 1)
    # Gate compatibility: tools/ab_gate.py reads compile_ok=false as a
    # measured infeasibility (loss) when this artifact replaces an A/B's.
    art.setdefault("by_shape", {})
    with open(ns.out, "w") as f:
        json.dump(art, f, indent=1)
    print(f"[compile_smoke] {ns.family}: "
          f"{'OK' if art['compile_ok'] else 'FAIL'} "
          f"({art['elapsed_s']}s, backend={art.get('backend')})")
    if not art["compile_ok"]:
        print(art["error"].splitlines()[-1] if art.get("error") else "")
    return 0 if art["compile_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
