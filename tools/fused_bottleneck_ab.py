"""A/B the halo-tiled fused Pallas bottleneck against XLA's compilation
of the identical math, at ResNet-50's stride-1 identity bottleneck
shapes (the ~50% MFU path of docs/PERF.md "ImageNet MFU" — see
ops/fused_bottleneck.py).

Methodology matches tools/fused_block_ab.py: each arm chains L
sequential block applications inside ONE lax.scan dispatch with chained
inputs (XLA can neither hoist nor overlap iterations; per-dispatch
tunnel latency cannot mask per-block costs); the fwd_bwd arms
differentiate wrt the input AND all nine parameters so both sides
compute the full gradient set; timing is fetch-synced
(bench._fetch_sync); the JSON is rewritten after every shape so a
mid-run tunnel death preserves finished shapes.

    python tools/fused_bottleneck_ab.py [--out JSON] [--length 8] [--reps 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (batch, spatial, f): rn50's three fusable identity-bottleneck stage
# shapes (f=512 @ 7² excluded — weights alone exceed VMEM; see module
# docstring). Tile plans come from fused_bottleneck._DEFAULT_TILES.
SHAPES = [(128, 56, 64), (128, 28, 128), (128, 14, 256)]

PARAM_KEYS = ("w1", "w2", "w3", "s1", "b1", "s2", "b2", "s3", "b3")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--length", type=int, default=8,
                    help="blocks chained per dispatch")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=None,
                    help="override the per-shape batch (tiny-config tests)")
    ap.add_argument("--shapes", default=None,
                    help="override as b,h,f[;b,h,f...]")
    ap.add_argument("--batch-tile", type=int, default=None)
    ap.add_argument("--row-tile", type=int, default=None)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    args = ap.parse_args()
    if args.length < 1 or args.reps < 1:
        raise SystemExit("--length and --reps must be >= 1")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from tpu_resnet.ops.fused_bottleneck import (
        bottleneck_apply, bottleneck_fwd, bottleneck_fwd_reference,
        bottleneck_train_apply, bottleneck_train_fwd,
        bottleneck_train_fwd_reference)

    shapes = SHAPES
    if args.shapes:
        shapes = [tuple(int(v) for v in s.split(","))
                  for s in args.shapes.split(";")]
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    out = {"device": jax.devices()[0].device_kind, "length": args.length,
           "dtype": args.dtype, "by_shape": {}}

    def flush():
        if args.out:
            json.dump(out, open(args.out, "w"), indent=2)

    for b, h, f in shapes:
        b = args.batch or b
        c4 = 4 * f
        key = f"b{b}_{h}x{h}x{c4}_f{f}"
        try:
            rng = np.random.default_rng(f)
            x0 = jnp.asarray(rng.normal(size=(b, h, h, c4)), dtype)
            # Tiny weights: L chained residual blocks must stay finite.
            params = (
                jnp.asarray(rng.normal(size=(c4, f)) * 0.01, dtype),
                jnp.asarray(rng.normal(size=(3, 3, f, f)) * 0.01, dtype),
                jnp.asarray(rng.normal(size=(f, c4)) * 0.01, dtype),
                jnp.ones((c4,), dtype), jnp.zeros((c4,), dtype),
                jnp.ones((f,), dtype), jnp.zeros((f,), dtype),
                jnp.ones((f,), dtype), jnp.zeros((f,), dtype))

            def chained(block):
                @jax.jit
                def run(x):
                    def body(xc, _):
                        return block(xc, *params), None
                    xc, _ = jax.lax.scan(body, x, None, length=args.length)
                    return jnp.float32(jnp.sum(xc))
                return run

            def chained_grad(block):
                def loss(x, *p):
                    def body(xc, _):
                        return block(xc, *p), None
                    xc, _ = jax.lax.scan(body, x, None, length=args.length)
                    return jnp.float32(jnp.sum(xc))

                g = jax.grad(loss, argnums=tuple(range(1 + len(params))))

                @jax.jit
                def run(x):
                    grads = g(x, *params)
                    return sum(jnp.float32(jnp.sum(gr)) for gr in grads)
                return run

            def time_arm(run):
                bench._fetch_sync(run(x0))  # compile + warm
                best = float("inf")
                for _ in range(args.reps):
                    t0 = time.perf_counter()
                    bench._fetch_sync(run(x0))
                    best = min(best, time.perf_counter() - t0)
                return best / args.length * 1e6  # us per block

            entry = {}
            pallas_us = time_arm(chained(
                lambda x, *p: bottleneck_fwd(
                    x, *p, batch_tile=args.batch_tile,
                    row_tile=args.row_tile)))
            xla_us = time_arm(chained(bottleneck_fwd_reference))
            entry["fwd"] = {
                "pallas_us_per_block": round(pallas_us, 2),
                "xla_us_per_block": round(xla_us, 2),
                "speedup": round(xla_us / pallas_us, 3)}
            out["by_shape"][key] = entry
            flush()  # fwd numbers survive a bwd failure

            pallas_g_us = time_arm(chained_grad(
                lambda x, *p: bottleneck_apply(
                    x, *p, args.batch_tile, args.row_tile, None)))
            xla_g_us = time_arm(chained_grad(bottleneck_fwd_reference))
            entry["fwd_bwd"] = {
                "pallas_us_per_block": round(pallas_g_us, 2),
                "xla_us_per_block": round(xla_g_us, 2),
                "speedup": round(xla_g_us / pallas_g_us, 3)}
            flush()

            # Training direction with LIVE batch stats (staged stats
            # passes + folded apply; four-pass correction backward) —
            # the numbers that would decide model integration. The live
            # blocks return (y, moments); dropping the moments ([0])
            # reuses the folded-arm harnesses, and the folded arm's
            # identity scale/bias double as raw BN gamma/beta here.
            pallas_t_us = time_arm(chained(
                lambda x, *p: bottleneck_train_fwd(
                    x, *p, batch_tile=args.batch_tile,
                    row_tile=args.row_tile)[0]))
            xla_t_us = time_arm(chained(
                lambda x, *p: bottleneck_train_fwd_reference(x, *p)[0]))
            entry["train_fwd_live_bn"] = {
                "pallas_us_per_block": round(pallas_t_us, 2),
                "xla_us_per_block": round(xla_t_us, 2),
                "speedup": round(xla_t_us / pallas_t_us, 3)}
            flush()

            pallas_tg_us = time_arm(chained_grad(
                lambda x, *p: bottleneck_train_apply(
                    x, *p, 1e-5, args.batch_tile, args.row_tile,
                    None)[0]))
            xla_tg_us = time_arm(chained_grad(
                lambda x, *p: bottleneck_train_fwd_reference(x, *p)[0]))
            entry["train_fwd_bwd_live_bn"] = {
                "pallas_us_per_block": round(pallas_tg_us, 2),
                "xla_us_per_block": round(xla_tg_us, 2),
                "speedup": round(xla_tg_us / pallas_tg_us, 3)}
        except Exception as e:  # record and keep measuring other shapes
            out["by_shape"].setdefault(key, {})["error"] = (
                f"{type(e).__name__}: {e}"[:500])
            traceback.print_exc()
        print(key, out["by_shape"][key], flush=True)
        flush()

    print(json.dumps(out))
    flush()


if __name__ == "__main__":
    main()
