#!/usr/bin/env bash
# CPU understudy of the recipe rehearsal (VERDICT r4 item 6): the full
# 90k-step cadence (battery stage 70) stays armed for the chip; this runs
# the SAME orchestration — piecewise-LR boundaries, checkpoint cadence,
# eval sidecar, resume-across-interruption, decay-boundary extraction —
# compressed to CPU scale, so the machinery is proven even if no live
# window ever opens.
#
# Two-phase on purpose: phase 1 is killed mid-run (a simulated window
# close / preemption); phase 2 must RESUME from the latest checkpoint —
# the log line "resumed from step N" (train/loop.py) and a
# monotonically-continuing step series are the proof, recorded in the
# summary as resume_proven.
#
#   tools/recipe_rehearsal_understudy.sh [DEST] [STEPS B1 B2 B3 CKPT]
#
# Defaults: 900 steps, boundaries 400/600/800, ckpt every 100 — the same
# 5:45/60/90-ish proportions as the real 90k/40k/60k/80k/1000 recipe.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
DEST="${1:-$REPO/docs/runs/recipe_rehearsal_cpu_r${RND}}"
STEPS="${2:-900}"; B1="${3:-400}"; B2="${4:-600}"; B3="${5:-800}"
CKPT="${6:-100}"
# Phase 1 must LIVE past the first checkpoint (step CKPT) or phase 2 has
# nothing to resume from: at the 1-core box's measured ~0.54 st/s plus
# ~40 s of compile, 300 s lands at step ~140 > 100.
PHASE1_TIMEOUT="${PHASE1_TIMEOUT:-300}"
RUN="${RUN_DIR:-/tmp/recipe_rehearsal_cpu}"
mkdir -p "$DEST"
cd "$REPO"

# Scrubbed CPU env (the axon plugin hangs a down tunnel): the same
# scrub bench.py's CPU child uses, via tpu_resnet.hostenv.
run_trainer() {
  local subcmd="$1" tmo="$2"
  timeout -k 15 "$tmo" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m tpu_resnet "$subcmd" --preset smoke \
    data.synthetic_learnable=true data.synthetic_task=freq100 \
    data.synthetic_classes=100 data.synthetic_label_noise=0.1 \
    data.synthetic_train_examples=2048 data.synthetic_eval_examples=512 \
    model.resnet_size=8 model.compute_dtype=float32 \
    train.global_batch_size=32 train.eval_batch_size=32 \
    train.train_steps="$STEPS" train.checkpoint_every="$CKPT" \
    train.log_every=20 train.image_summary_every=0 \
    optim.schedule=cifar_piecewise "optim.boundaries=($B1,$B2,$B3)" \
    "optim.values=(0.1,0.01,0.001,0.0001)" \
    train.train_dir="$RUN"
}

rm -rf "$RUN"
echo "[understudy] phase 1: train until interrupted (${PHASE1_TIMEOUT}s)"
set +e
run_trainer train "$PHASE1_TIMEOUT" > "$DEST/phase1.log" 2>&1
p1=$?
set -e
tail -3 "$DEST/phase1.log" || true
if [ "$p1" -eq 0 ]; then
  echo "[understudy] phase 1 finished before the interrupt — increase" \
       "STEPS or lower PHASE1_TIMEOUT for a real resume proof"
fi

echo "[understudy] phase 2: train_and_eval resumes to completion"
run_trainer train_and_eval 3600 > "$DEST/phase2.log" 2>&1
tail -5 "$DEST/phase2.log"

RESUME=""
if grep -q "resumed from step" "$DEST/phase2.log"; then
  RESUME="--resume-proven"
  echo "[understudy] resume across interruption: PROVEN"
else
  echo "[understudy] WARNING: no resume line in phase 2 (phase 1 too short?)"
fi

cp "$RUN/metrics.jsonl" "$DEST/train_metrics.jsonl"
cp "$RUN/eval/metrics.jsonl" "$DEST/eval_metrics.jsonl" 2>/dev/null || true
cp "$RUN/eval/best_precision.json" "$DEST/" 2>/dev/null || true

python tools/rehearsal_summary.py "$DEST" "$B1" "$B2" "$B3" "$CKPT" \
  $RESUME \
  --what "CPU understudy of the 40k/60k/80k recipe orchestration (compressed ${STEPS}-step run, boundaries $B1/$B2/$B3, ckpt every $CKPT, interrupt+resume, live eval sidecar)"
