"""Load generator + scenario suite for the serving stack (tpu_resnet/
serve: one replica, or the fleet behind ``tpu_resnet route``).

Hammers ``POST /predict`` with concurrent clients and reports serving
throughput + latency percentiles the same way ``bench.py`` reports
training: one machine-parseable ``RESULT_JSON:`` line, emitted through
bench's hardened single-write path (atomic on pipes, so a killed run
leaves either a whole line or a truncated one the salvage parser skips —
never a corrupt-but-parseable one).

Two traffic models:

``--mode closed`` (default)  N clients in a closed loop: each fires its
    next request the moment the previous one returns. Measures capacity —
    max sustainable throughput at concurrency N.
``--mode open``  N clients paced to a global ``--qps`` arrival rate,
    independent of response times (requests queue up when the server
    falls behind). Measures latency under a fixed offered load — the
    shape real user traffic has.

Scenarios (``--scenario``; each emits RESULT_JSON that ``perfwatch
--sweep`` can gate — the result carries a sweep-shaped ``points`` list):

``steady``        the plain load above (default).
``burst``         open-loop square wave: offered qps alternates between
                  0.25x and 2x ``--qps`` in quarter-duration phases.
``ramp``          diurnal ramp: offered qps follows a half-sine from
                  0.2x up through 1x and back down over the run.
``diurnal``       sine-on-a-ramp: a 0.3x->1x rising baseline carrying
                  two full day/night sine cycles — deterministic and
                  resumable (pure function of run fraction), the
                  arrival schedule the autoscale_diurnal scenario
                  drives the autopilot with (docs/AUTOPILOT.md).
``slow_client``   2 byte-trickling clients (raw sockets, body sent in
                  delayed chunks) run BESIDE the normal fleet traffic;
                  their tally is reported separately — the check is that
                  normal clients keep their latency while handler
                  threads are held open.
``mixed_lane``    odd clients send ``X-Lane: batch``, even clients stay
                  interactive; per-lane p50/p99 in the result (the lane
                  priority + SLO shedding probe).
``replica_kill``  chaos: SIGKILL one replica (pid from ``--fleet-dir``
                  discovery) at half-duration while traffic runs — the
                  headline drill: a router in front must keep failures
                  at zero beyond the in-flight retry window.
``rolling_drain`` operations: drain each replica in turn through the
                  router's admin endpoint (``--router-url`` or
                  route.json in ``--fleet-dir``) while traffic runs —
                  the zero-failed-requests rolling-upgrade drill.

Client-side failure classes are DISTINCT in the result: ``failed``
(unexpected HTTP status), ``timeouts`` (request exceeded ``--deadline-ms``
/ ``--timeout``), ``connect_failures`` (refused/reset). A refused
connection and a slow reply are different fleet bugs.

A/B mode (``--ab URL_B`` or ``--ab-name NAME``): drive TWO endpoints
with the IDENTICAL paired load — same clients, pacing, request bodies
and seed, run sequentially so the arms never contend for client CPU —
and emit ONE RESULT_JSON with both tallies under ``arms.a``/``arms.b``
plus a ``delta`` block of B-over-A ratios. Arm labels come from each
endpoint's own ``/info`` (``quantize`` when not "off", else
``compute_dtype``), so a quantized-vs-bf16 comparison labels itself
with no out-of-band config — the int8 serve arm's gate rides this
(scenarios/quant_ab_probe.json).

Usage:
    python tools/loadgen.py --url http://127.0.0.1:PORT [--clients 8]
        [--duration 10] [--mode closed|open] [--qps 100]
        [--scenario steady] [--deadline-ms 0] [--fleet-dir DIR]
        [--images-per-request 1] [--out result.json]
    python tools/loadgen.py --train-dir /tmp/run   # port from route.json
                                                   # (falls back to serve.json)

Exit code 0 = ran with zero failures/timeouts/connect-failures, 1 = any
(``--allow-rejects`` downgrades 429s to a count — expected when probing
the backpressure/shedding contracts), 2 = could not reach the server.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from bench import _print_line  # noqa: E402  (hardened single-write emit)
from tpu_resnet.obs.server import parse_prometheus  # noqa: E402
from tpu_resnet.serve.batcher import percentile  # noqa: E402

SCENARIOS = ("steady", "burst", "ramp", "diurnal", "slow_client",
             "mixed_lane", "replica_kill", "rolling_drain")


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _scrape_metrics(base: str) -> dict:
    try:
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            return parse_prometheus(r.read().decode())
    except (OSError, ValueError):
        return {}


def qps_factor(scenario: str, frac: float) -> float:
    """Offered-load multiplier at run fraction ``frac`` (0..1). Pure —
    the scenario schedules are unit-tested against this directly."""
    frac = min(max(frac, 0.0), 1.0)
    if scenario == "burst":
        # Quarter-duration square wave: calm, burst, calm, burst.
        return 2.0 if int(frac * 4) % 2 else 0.25
    if scenario == "ramp":
        # Diurnal half-sine: trough -> peak -> trough.
        return 0.2 + 0.8 * math.sin(math.pi * frac)
    if scenario == "diurnal":
        # Sine-on-a-ramp: a rising baseline (the "growing user base")
        # carrying two full day/night cycles — the autoscale_diurnal
        # drill wants repeated up AND down swings with a drifting mean,
        # so an autopilot that only handles one burst shape flunks.
        # Pure function of frac: the schedule is deterministic and
        # resumable (restart at frac f, get the same curve).
        ramp = 0.3 + 0.7 * frac
        wave = 1.0 + 0.6 * math.sin(2.0 * math.pi * 2.0 * frac)
        return max(0.05, ramp * wave)
    return 1.0


class ClientStats:
    """Per-client tally merged at the end (no cross-thread locking in the
    request path)."""

    SLOWEST_K = 8

    def __init__(self, lane: str = "interactive", client_id: int = 0):
        self.lane = lane
        self.client_id = client_id
        self.latencies_ms = []
        self.ok = 0
        self.rejected = 0          # 429 backpressure / shed
        self.failed = 0            # unexpected HTTP status
        self.timeouts = 0          # blew the per-request deadline
        self.connect_failures = 0  # refused / reset / unreachable
        self.images = 0
        self.seq = 0
        self.slowest = []          # (latency_ms, trace_id) worst-K heap

    def mint_trace(self) -> str:
        """Client-side trace id, stamped on the request as X-Trace-Id so
        the router/replica span lanes and this client's latency tally
        name the same request. Deterministic per (client, seq) — rerun
        the same seed and the ids line up."""
        self.seq += 1
        return f"lg{self.client_id:x}-{self.seq:x}"

    def note_trace(self, trace_id: str, dt_ms: float) -> None:
        """Track the worst-K requests this client saw (timeouts count —
        they ARE the tail). Merged and reported as
        ``slowest_traces`` in RESULT_JSON: the ids to grep for in
        ``trace-export``'s request lanes."""
        self.slowest.append((dt_ms, trace_id))
        if len(self.slowest) > self.SLOWEST_K:
            self.slowest.sort(reverse=True)
            del self.slowest[self.SLOWEST_K:]


def _fire(url: str, body: bytes, shape: str, timeout: float,
          lane: str = "interactive", trace_id: str = "") -> int:
    """One predict. Returns the HTTP status, -2 for a client-side
    timeout, -1 for a connect failure."""
    headers = {"Content-Type": "application/octet-stream",
               "X-Shape": shape}
    if lane != "interactive":
        headers["X-Lane"] = lane
    if trace_id:
        headers["X-Trace-Id"] = trace_id
    req = urllib.request.Request(url + "/predict", data=body,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except urllib.error.URLError as e:
        reason = getattr(e, "reason", None)
        return -2 if isinstance(reason, TimeoutError) else -1
    except TimeoutError:     # socket.timeout is an alias since 3.10
        return -2
    except OSError:
        return -1


def _note(stats: ClientStats, status: int, n: int, dt_ms: float) -> None:
    if status == 200:
        stats.ok += 1
        stats.images += n
        stats.latencies_ms.append(dt_ms)
    elif status == 429:
        stats.rejected += 1
    elif status == -2:
        stats.timeouts += 1
    elif status == -1:
        stats.connect_failures += 1
    else:
        stats.failed += 1


def _client_loop(url: str, images: np.ndarray, t_start: float,
                 duration: float, stats: ClientStats, interval: float,
                 start_at: float, timeout: float, scenario: str) -> None:
    body = images.tobytes()
    shape = ",".join(str(d) for d in images.shape)
    n = images.shape[0]
    deadline = t_start + duration
    next_at = start_at
    while True:
        now = time.monotonic()
        if now >= deadline:
            return
        if interval > 0:      # open loop: scenario-shaped arrival rate
            if next_at > now:
                time.sleep(min(next_at - now, deadline - now))
                if time.monotonic() >= deadline:
                    return
            factor = max(qps_factor(scenario,
                                    (time.monotonic() - t_start)
                                    / duration), 1e-3)
            next_at += interval / factor
        t0 = time.monotonic()
        trace_id = stats.mint_trace()
        status = _fire(url, body, shape, timeout, lane=stats.lane,
                       trace_id=trace_id)
        dt_ms = (time.monotonic() - t0) * 1e3
        _note(stats, status, n, dt_ms)
        stats.note_trace(trace_id, dt_ms)


def _slow_client_loop(host: str, port: int, body: bytes, shape: str,
                      deadline: float, stats: ClientStats,
                      chunk_delay: float = 0.25) -> None:
    """A byte-trickling client: sends the request body in delayed chunks
    over a raw socket, holding a server handler thread open the whole
    time — the classic slowloris-shaped tenant a fleet must tolerate."""
    step = max(1, len(body) // 8)
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        trace_id = stats.mint_trace()
        head = (f"POST /predict HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Type: application/octet-stream\r\n"
                f"X-Shape: {shape}\r\nX-Trace-Id: {trace_id}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n").encode()
        try:
            with socket.create_connection((host, port), timeout=10) as s:
                s.sendall(head)
                for i in range(0, len(body), step):
                    if time.monotonic() >= deadline:
                        return
                    s.sendall(body[i:i + step])
                    time.sleep(chunk_delay)
                s.settimeout(30)
                resp = b""
                while b"\r\n" not in resp:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    resp += chunk
                status_line = resp.split(b"\r\n", 1)[0].split()
                status = int(status_line[1]) if len(status_line) > 1 else 0
                dt_ms = (time.monotonic() - t0) * 1e3
                _note(stats, status if status else -1, 1, dt_ms)
                stats.note_trace(trace_id, dt_ms)
        except TimeoutError:
            stats.timeouts += 1
        except (OSError, ValueError, IndexError):
            stats.connect_failures += 1


# ------------------------------------------------------------ fleet chaos
def _fleet_records(fleet_dir: str):
    from tpu_resnet.serve.router import discover_replicas

    return discover_replicas(fleet_dir) if fleet_dir else []


def _kill_one_replica(fleet_dir: str):
    """SIGKILL the first live replica found in the fleet discovery —
    the hard mid-traffic death the failover drill rides."""
    for rec in _fleet_records(fleet_dir):
        pid = rec.get("pid")
        if not pid:
            continue
        try:
            os.kill(int(pid), 0)
        except (OSError, ValueError):
            continue
        os.kill(int(pid), signal.SIGKILL)
        return {"replica": rec["name"], "pid": pid}
    return None


def _chaos_thread(scenario: str, fleet_dir: str, router_url: str,
                  t_start: float, duration: float, drain_interval: float,
                  record: dict) -> None:
    if scenario == "replica_kill":
        time.sleep(max(0.0, t_start + duration / 2 - time.monotonic()))
        record["killed"] = _kill_one_replica(fleet_dir)
        record["killed_at_sec"] = round(time.monotonic() - t_start, 2)
    elif scenario == "rolling_drain":
        from tpu_resnet.serve.router import request_drain

        names = [r["name"] for r in _fleet_records(fleet_dir)]
        record["drains"] = []
        interval = drain_interval or duration / (len(names) + 1)
        for name in names:
            time.sleep(interval)
            if time.monotonic() >= t_start + duration:
                break
            out = request_drain(router_url, name)
            record["drains"].append(
                {"replica": name, "at_sec":
                 round(time.monotonic() - t_start, 2), **out})


def _lane_summary(stats_list) -> dict:
    out = {}
    for lane in sorted({st.lane for st in stats_list}):
        group = [st for st in stats_list if st.lane == lane]
        lat = sorted(x for st in group for x in st.latencies_ms)
        out[lane] = {
            "requests_ok": sum(st.ok for st in group),
            "rejected_429": sum(st.rejected for st in group),
            "failed": sum(st.failed for st in group),
            "timeouts": sum(st.timeouts for st in group),
            "connect_failures": sum(st.connect_failures for st in group),
            "p50_ms": round(percentile(lat, 0.50), 2),
            "p99_ms": round(percentile(lat, 0.99), 2),
        }
    return out


def run_load(url: str, clients: int = 8, duration: float = 10.0,
             mode: str = "closed", qps: float = 100.0,
             images_per_request: int = 1, image_size: int = 0,
             timeout: float = 30.0, seed: int = 0,
             scenario: str = "steady", deadline_ms: float = 0.0,
             fleet_dir: str = "", router_url: str = "",
             drain_interval: float = 0.0, slow_clients: int = 2) -> dict:
    """Drive the server; returns the result dict (see RESULT_JSON)."""
    if scenario not in SCENARIOS:
        raise ValueError(f"unknown scenario {scenario!r}; have "
                         f"{SCENARIOS}")
    if scenario in ("burst", "ramp", "diurnal"):
        mode = "open"  # a shaped offered load needs open-loop pacing
    if scenario in ("replica_kill", "rolling_drain") and not fleet_dir:
        raise ValueError(f"scenario {scenario} needs --fleet-dir (the "
                         f"replicas' discovery directory)")
    url = url.rstrip("/")
    if scenario == "rolling_drain" and not router_url:
        router_url = url  # drains go through the router we're driving
    info = _get_json(url + "/info")
    # A replica /info carries image_shape directly; the router forwards
    # the shape its probes learned (None until the first healthy probe).
    if info.get("image_shape"):
        h, w, c = info["image_shape"]
    elif image_size:
        h = w = image_size
        c = 3
    else:
        raise ValueError("target /info carries no image_shape yet — "
                         "pass --image-size")
    if image_size and image_size != h:
        raise ValueError(f"--image-size {image_size} != server model "
                         f"input {h}")
    request_timeout = deadline_ms / 1e3 if deadline_ms > 0 else timeout
    metrics_before = _scrape_metrics(url)
    rng = np.random.RandomState(seed)
    interval = clients / qps if mode == "open" else 0.0
    t_start = time.monotonic()
    deadline = t_start + duration
    stats, threads = [], []
    chaos_record: dict = {}
    for i in range(clients):
        lane = ("batch" if scenario == "mixed_lane" and i % 2
                else "interactive")
        st = ClientStats(lane=lane, client_id=i)
        stats.append(st)
        images = rng.randint(0, 255, (images_per_request, h, w, c)
                             ).astype(np.uint8)
        # Open loop: stagger client phases so the aggregate arrival
        # process is uniform at ``qps``, not ``clients`` synchronized
        # bursts.
        start_at = t_start + (interval * i / clients if interval else 0.0)
        threads.append(threading.Thread(
            target=_client_loop,
            args=(url, images, t_start, duration, st, interval, start_at,
                  request_timeout, scenario), daemon=True))
    slow_stats = []
    if scenario == "slow_client":
        host = url.split("://", 1)[-1].rsplit(":", 1)[0]
        port = int(url.rsplit(":", 1)[-1])
        body = rng.randint(0, 255, (1, h, w, c)).astype(np.uint8).tobytes()
        for j in range(max(1, slow_clients)):
            st = ClientStats(lane="slow", client_id=clients + j)
            slow_stats.append(st)
            threads.append(threading.Thread(
                target=_slow_client_loop,
                args=(host, port, body, f"1,{h},{w},{c}", deadline, st),
                daemon=True))
    if scenario in ("replica_kill", "rolling_drain"):
        threads.append(threading.Thread(
            target=_chaos_thread,
            args=(scenario, fleet_dir, router_url, t_start, duration,
                  drain_interval, chaos_record), daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration + request_timeout + 30)
    wall = time.monotonic() - t_start

    lat = sorted(x for st in stats for x in st.latencies_ms)
    ok = sum(st.ok for st in stats)
    rejected = sum(st.rejected for st in stats)
    failed = sum(st.failed for st in stats)
    timeouts = sum(st.timeouts for st in stats)
    connect_failures = sum(st.connect_failures for st in stats)
    images = sum(st.images for st in stats)
    metrics = _scrape_metrics(url)
    ns = "tpu_resnet_"
    throughput = round(ok / max(wall, 1e-9), 2)
    hard_failures = failed + timeouts + connect_failures
    result = {
        "scenario": scenario,
        "mode": mode, "clients": clients, "duration_sec": round(wall, 2),
        # Correlation id of the served train_dir (serve /info exposes the
        # run_id obs/manifest.py minted) — joins this RESULT_JSON to the
        # same trace-export timeline as the trainer/eval/serve events.
        "run_id": info.get("run_id"),
        "images_per_request": images_per_request,
        "offered_qps": qps if mode == "open" else None,
        "deadline_ms": deadline_ms or None,
        "requests_ok": ok, "rejected_429": rejected, "failed": failed,
        "timeouts": timeouts, "connect_failures": connect_failures,
        "throughput_rps": throughput,
        "images_per_sec": round(images / max(wall, 1e-9), 2),
        "latency_ms": {
            "p50": round(percentile(lat, 0.50), 2),
            "p95": round(percentile(lat, 0.95), 2),
            "p99": round(percentile(lat, 0.99), 2),
            "mean": round(float(np.mean(lat)), 2) if lat else 0.0,
            "max": round(lat[-1], 2) if lat else 0.0,
        },
        # Sweep-shaped point so ``tools/perfwatch.py --sweep`` ingests
        # scenario results as a tracked trajectory with zero glue: the
        # point id cohorts runs of the same scenario across rounds.
        "points": [{
            "id": f"scenario={scenario}", "status":
                "ok" if hard_failures == 0 and ok > 0 else "error",
            "backend": "serve", "steps_per_sec": throughput,
        }],
        "backend": "serve",
        # Worst requests by client-observed latency, by the trace ids
        # this client stamped — paste one into trace-export's request
        # lanes to see where that exact request spent its time.
        "slowest_traces": [
            {"trace_id": tid, "latency_ms": round(ms, 2)}
            for ms, tid in sorted(
                (p for st in stats + slow_stats for p in st.slowest),
                reverse=True)[:5]],
        "server": {
            "model_step": info.get("model_step"),
            "observed_mean_batch": round(
                metrics.get(ns + "serve_batch_size_mean", 0.0), 3),
            "pad_fraction": round(
                metrics.get(ns + "serve_pad_fraction", 0.0), 4),
            "reloads": int(metrics.get(ns + "serve_reloads_total", 0)),
            "requests_total": int(
                metrics.get(ns + "serve_requests_total", 0)
                - metrics_before.get(ns + "serve_requests_total", 0)),
        },
    }
    if scenario == "mixed_lane":
        result["lanes"] = _lane_summary(stats)
    if slow_stats:
        result["slow_clients"] = _lane_summary(slow_stats).get("slow", {})
    if chaos_record:
        result["chaos"] = chaos_record
    # Router-side view when the target IS the router (route_* series).
    if ns + "route_requests_total" in metrics:
        result["router"] = {
            "retries": int(metrics.get(ns + "route_retries_total", 0)),
            "hedges": int(metrics.get(ns + "route_hedges_total", 0)),
            "shed": int(metrics.get(ns + "route_shed_total", 0)),
            "replicas_healthy": int(
                metrics.get(ns + "route_replicas_healthy", 0)),
            "p99_ms": round(metrics.get(ns + "route_p99_ms", 0.0), 2),
        }
    return result


# ------------------------------------------------------------- A/B mode
AB_SCENARIOS = ("steady", "burst", "ramp", "mixed_lane")


def _arm_label(url: str, fallback: str) -> str:
    """Self-reported arm label from the endpoint's /info: the quant mode
    when quantized, else the compute dtype — no out-of-band config."""
    try:
        info = _get_json(url.rstrip("/") + "/info")
    except (OSError, ValueError):
        return fallback
    q = info.get("quantize", "off")
    if q and q != "off":
        return str(q)
    return str(info.get("compute_dtype") or fallback)


def run_ab(url_a: str, url_b: str, **kw) -> dict:
    """Paired A/B: run_load twice with identical kwargs (same seed →
    byte-identical request bodies and pacing), sequentially, and merge
    into one result. Top-level failure counters are the SUM of both
    arms, so the exit-code contract and the scenario conductor's
    ``loadgen_result`` checker gate both arms at once."""
    if kw.get("scenario", "steady") not in AB_SCENARIOS:
        raise ValueError(f"--ab supports scenarios {AB_SCENARIOS}; the "
                         f"chaos scenarios mutate the fleet and would "
                         f"not give arm B the same world as arm A")
    label_a = _arm_label(url_a, "a")
    label_b = _arm_label(url_b, "b")
    if label_a == label_b:
        label_a, label_b = label_a + "_a", label_b + "_b"
    res_a = run_load(url_a, **kw)
    res_b = run_load(url_b, **kw)
    scenario = res_a["scenario"]
    totals = {k: res_a[k] + res_b[k]
              for k in ("requests_ok", "rejected_429", "failed",
                        "timeouts", "connect_failures")}
    ta, tb = res_a["throughput_rps"], res_b["throughput_rps"]
    pa = res_a["latency_ms"]["p99"]
    pb = res_b["latency_ms"]["p99"]
    hard = (totals["failed"] + totals["timeouts"]
            + totals["connect_failures"])
    return {
        "ab": True,
        "scenario": scenario,
        "mode": res_a["mode"], "clients": res_a["clients"],
        "seed": kw.get("seed", 0),
        "arms": {"a": dict(res_a, arm=label_a, url=url_a),
                 "b": dict(res_b, arm=label_b, url=url_b)},
        **totals,
        # B-over-A ratios: >1 throughput / <1 p99 means arm B wins.
        "delta": {
            "throughput_rps_b_over_a":
                round(tb / ta, 4) if ta else None,
            "p99_ms_b_over_a": round(pb / pa, 4) if pa else None,
        },
        # One paired point per arm: perfwatch cohorts each arm's
        # trajectory separately under the same scenario id.
        "points": [
            {"id": f"scenario={scenario}:arm={label_a}",
             "status": "ok" if hard == 0 and res_a["requests_ok"] > 0
             else "error", "backend": "serve", "steps_per_sec": ta},
            {"id": f"scenario={scenario}:arm={label_b}",
             "status": "ok" if hard == 0 and res_b["requests_ok"] > 0
             else "error", "backend": "serve", "steps_per_sec": tb},
        ],
        "backend": "serve",
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="",
                    help="server/router base url (http://host:port)")
    ap.add_argument("--train-dir", default="",
                    help="discover the port from <train-dir>/route.json "
                         "(router, preferred) or serve.json")
    ap.add_argument("--name", default="",
                    help="drive a NAMED replica instead: discover its "
                         "port from <train-dir>/serve-<name>.json")
    ap.add_argument("--ab", default="", metavar="URL_B",
                    help="A/B mode: also drive this endpoint with the "
                         "identical paired load; one RESULT_JSON with "
                         "arms.a/arms.b and B-over-A deltas")
    ap.add_argument("--ab-name", default="",
                    help="A/B mode with discovery: arm B is the named "
                         "replica's serve-<name>.json under --train-dir")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--mode", choices=["closed", "open"], default="closed")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop aggregate arrival rate")
    ap.add_argument("--scenario", choices=list(SCENARIOS),
                    default="steady",
                    help="traffic/chaos scenario (see module docstring)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request client budget; a reply past it "
                         "counts in the distinct 'timeouts' field "
                         "(0 = use --timeout)")
    ap.add_argument("--fleet-dir", default="",
                    help="replica discovery dir (serve-*.json) for the "
                         "chaos scenarios; defaults to --train-dir")
    ap.add_argument("--router-url", default="",
                    help="rolling_drain: router admin base url (default: "
                         "the --url target)")
    ap.add_argument("--drain-interval", type=float, default=0.0,
                    help="rolling_drain: seconds between drains "
                         "(0 = duration/(replicas+1))")
    ap.add_argument("--slow-clients", type=int, default=2,
                    help="slow_client scenario: byte-trickling clients")
    ap.add_argument("--images-per-request", type=int, default=1)
    ap.add_argument("--image-size", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--allow-rejects", action="store_true",
                    help="429s don't fail the run (backpressure/shed "
                         "probes)")
    ap.add_argument("--out", default="",
                    help="also write the result json to this path "
                         "(atomic tmp+rename)")
    args = ap.parse_args(argv)

    def named_port(name: str):
        from tpu_resnet.serve.discovery import read_port
        return read_port(args.train_dir, f"serve-{name}.json")

    url = args.url
    fleet_dir = args.fleet_dir or args.train_dir
    if not url:
        if not args.train_dir:
            ap.error("need --url or --train-dir")
        if args.name:
            port = named_port(args.name)
        else:
            from tpu_resnet.serve.router import read_route_port
            from tpu_resnet.serve.server import read_serve_port
            port = read_route_port(args.train_dir)
            if port is None:
                port = read_serve_port(args.train_dir)
        if port is None:
            print(f"[loadgen] no discovery file under "
                  f"{args.train_dir}"
                  + (f" for replica {args.name!r}" if args.name else ""),
                  file=sys.stderr)
            return 2
        url = f"http://127.0.0.1:{port}"

    ab_url = args.ab
    if args.ab_name:
        if not args.train_dir:
            ap.error("--ab-name needs --train-dir for discovery")
        port_b = named_port(args.ab_name)
        if port_b is None:
            print(f"[loadgen] no serve-{args.ab_name}.json under "
                  f"{args.train_dir}", file=sys.stderr)
            return 2
        ab_url = f"http://127.0.0.1:{port_b}"

    kw = dict(clients=args.clients, duration=args.duration,
              mode=args.mode, qps=args.qps, scenario=args.scenario,
              deadline_ms=args.deadline_ms, fleet_dir=fleet_dir,
              router_url=args.router_url,
              drain_interval=args.drain_interval,
              slow_clients=args.slow_clients,
              images_per_request=args.images_per_request,
              image_size=args.image_size, timeout=args.timeout,
              seed=args.seed)
    try:
        result = run_ab(url, ab_url, **kw) if ab_url \
            else run_load(url, **kw)
    except (OSError, ValueError) as e:
        print(f"[loadgen] cannot drive {url}: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.out:
        tmp = args.out + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, args.out)
    _print_line("RESULT_JSON: " + json.dumps(result))
    bad = (result["failed"] + result["timeouts"]
           + result["connect_failures"]
           + (0 if args.allow_rejects else result["rejected_429"]))
    return 0 if bad == 0 and result["requests_ok"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
