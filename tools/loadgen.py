"""Load generator for the predict server (tpu_resnet/serve).

Hammers ``POST /predict`` with concurrent clients and reports serving
throughput + latency percentiles the same way ``bench.py`` reports
training: one machine-parseable ``RESULT_JSON:`` line, emitted through
bench's hardened single-write path (atomic on pipes, so a killed run
leaves either a whole line or a truncated one the salvage parser skips —
never a corrupt-but-parseable one).

Two traffic models:

``--mode closed`` (default)  N clients in a closed loop: each fires its
    next request the moment the previous one returns. Measures capacity —
    max sustainable throughput at concurrency N.
``--mode open``  N clients paced to a global ``--qps`` arrival rate,
    independent of response times (requests queue up when the server
    falls behind). Measures latency under a fixed offered load — the
    shape real user traffic has.

After the run the server's ``/metrics`` is scraped so the report carries
the *server-side* view too: observed mean batch size (the dynamic
batcher's coalescing in action), pad fraction, rejected count.

Usage:
    python tools/loadgen.py --url http://127.0.0.1:PORT [--clients 8]
        [--duration 10] [--mode closed|open] [--qps 100]
        [--images-per-request 1] [--out result.json]
    python tools/loadgen.py --train-dir /tmp/run   # port from serve.json

Exit code 0 = ran with zero failed requests, 1 = any failure/rejection
(``--allow-rejects`` downgrades 429s to a count — expected when probing
the backpressure contract), 2 = could not reach the server.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from bench import _print_line  # noqa: E402  (hardened single-write emit)
from tpu_resnet.obs.server import parse_prometheus  # noqa: E402
from tpu_resnet.serve.batcher import percentile  # noqa: E402


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _scrape_metrics(base: str) -> dict:
    try:
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            return parse_prometheus(r.read().decode())
    except (OSError, ValueError):
        return {}


class ClientStats:
    """Per-client tally merged at the end (no cross-thread locking in the
    request path)."""

    def __init__(self):
        self.latencies_ms = []
        self.ok = 0
        self.rejected = 0   # 429 backpressure
        self.failed = 0     # anything else
        self.images = 0


def _fire(url: str, body: bytes, shape: str, timeout: float) -> int:
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/octet-stream",
                 "X-Shape": shape})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except OSError:
        return -1


def _client_loop(url: str, images: np.ndarray, deadline: float,
                 stats: ClientStats, interval: float, start_at: float,
                 timeout: float) -> None:
    body = images.tobytes()
    shape = ",".join(str(d) for d in images.shape)
    n = images.shape[0]
    next_at = start_at
    while True:
        now = time.monotonic()
        if now >= deadline:
            return
        if interval > 0:      # open loop: fixed arrival schedule
            if next_at > now:
                time.sleep(min(next_at - now, deadline - now))
                if time.monotonic() >= deadline:
                    return
            next_at += interval
        t0 = time.monotonic()
        status = _fire(url, body, shape, timeout)
        dt_ms = (time.monotonic() - t0) * 1e3
        if status == 200:
            stats.ok += 1
            stats.images += n
            stats.latencies_ms.append(dt_ms)
        elif status == 429:
            stats.rejected += 1
        else:
            stats.failed += 1


def run_load(url: str, clients: int = 8, duration: float = 10.0,
             mode: str = "closed", qps: float = 100.0,
             images_per_request: int = 1, image_size: int = 0,
             timeout: float = 30.0, seed: int = 0) -> dict:
    """Drive the server; returns the result dict (see RESULT_JSON)."""
    url = url.rstrip("/")
    info = _get_json(url + "/info")
    h, w, c = info["image_shape"]
    if image_size and image_size != h:
        raise ValueError(f"--image-size {image_size} != server model "
                         f"input {h}")
    metrics_before = _scrape_metrics(url)
    rng = np.random.RandomState(seed)
    interval = clients / qps if mode == "open" else 0.0
    t_start = time.monotonic()
    deadline = t_start + duration
    stats = [ClientStats() for _ in range(clients)]
    threads = []
    for i, st in enumerate(stats):
        images = rng.randint(0, 255, (images_per_request, h, w, c)
                             ).astype(np.uint8)
        # Open loop: stagger client phases so the aggregate arrival
        # process is uniform at ``qps``, not ``clients`` synchronized
        # bursts.
        start_at = t_start + (interval * i / clients if interval else 0.0)
        t = threading.Thread(target=_client_loop,
                             args=(url, images, deadline, st, interval,
                                   start_at, timeout), daemon=True)
        threads.append(t)
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration + timeout + 10)
    wall = time.monotonic() - t_start

    lat = sorted(x for st in stats for x in st.latencies_ms)
    ok = sum(st.ok for st in stats)
    rejected = sum(st.rejected for st in stats)
    failed = sum(st.failed for st in stats)
    images = sum(st.images for st in stats)
    metrics = _scrape_metrics(url)
    ns = "tpu_resnet_"
    result = {
        "mode": mode, "clients": clients, "duration_sec": round(wall, 2),
        # Correlation id of the served train_dir (serve /info exposes the
        # run_id obs/manifest.py minted) — joins this RESULT_JSON to the
        # same trace-export timeline as the trainer/eval/serve events.
        "run_id": info.get("run_id"),
        "images_per_request": images_per_request,
        "offered_qps": qps if mode == "open" else None,
        "requests_ok": ok, "rejected_429": rejected, "failed": failed,
        "throughput_rps": round(ok / max(wall, 1e-9), 2),
        "images_per_sec": round(images / max(wall, 1e-9), 2),
        "latency_ms": {
            "p50": round(percentile(lat, 0.50), 2),
            "p95": round(percentile(lat, 0.95), 2),
            "p99": round(percentile(lat, 0.99), 2),
            "mean": round(float(np.mean(lat)), 2) if lat else 0.0,
            "max": round(lat[-1], 2) if lat else 0.0,
        },
        "server": {
            "model_step": info.get("model_step"),
            "observed_mean_batch": round(
                metrics.get(ns + "serve_batch_size_mean", 0.0), 3),
            "pad_fraction": round(
                metrics.get(ns + "serve_pad_fraction", 0.0), 4),
            "reloads": int(metrics.get(ns + "serve_reloads_total", 0)),
            "requests_total": int(
                metrics.get(ns + "serve_requests_total", 0)
                - metrics_before.get(ns + "serve_requests_total", 0)),
        },
    }
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="",
                    help="server base url (http://host:port)")
    ap.add_argument("--train-dir", default="",
                    help="discover the port from <train-dir>/serve.json")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--mode", choices=["closed", "open"], default="closed")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop aggregate arrival rate")
    ap.add_argument("--images-per-request", type=int, default=1)
    ap.add_argument("--image-size", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--allow-rejects", action="store_true",
                    help="429s don't fail the run (backpressure probes)")
    ap.add_argument("--out", default="",
                    help="also write the result json to this path "
                         "(atomic tmp+rename)")
    args = ap.parse_args(argv)

    url = args.url
    if not url:
        if not args.train_dir:
            ap.error("need --url or --train-dir")
        from tpu_resnet.serve.server import read_serve_port
        port = read_serve_port(args.train_dir)
        if port is None:
            print(f"[loadgen] no serve.json under {args.train_dir}",
                  file=sys.stderr)
            return 2
        url = f"http://127.0.0.1:{port}"

    try:
        result = run_load(url, clients=args.clients,
                          duration=args.duration, mode=args.mode,
                          qps=args.qps,
                          images_per_request=args.images_per_request,
                          image_size=args.image_size,
                          timeout=args.timeout, seed=args.seed)
    except (OSError, ValueError) as e:
        print(f"[loadgen] cannot drive {url}: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.out:
        tmp = args.out + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, args.out)
    _print_line("RESULT_JSON: " + json.dumps(result))
    bad = result["failed"] + (0 if args.allow_rejects
                              else result["rejected_429"])
    return 0 if bad == 0 and result["requests_ok"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
