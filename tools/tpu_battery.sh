#!/usr/bin/env bash
# Measurement battery fired by launch/tpu_watch.sh when the TPU tunnel is
# live. Stages are checkpointed with marker files so a window that closes
# mid-battery resumes where it left off on the next live window instead of
# redoing finished work. Results are archived under docs/runs/.
#
# Round 4 restructure: the previously-hardcoded bench stage moved into
# tools/battery.d/10_bench.sh so filename order fully controls priority —
# the fused-block A/B (05_) is the round's decisive experiment (VERDICT r3
# item 1) and must own the front of the first live window, ahead of the
# headline bench.
#
# pipefail matters: stage results are piped through tee, and without it
# the `if` below tests tee's status — a failed stage would be marked done
# (exactly how the r3 stage-20 OOM slipped through on the first window).
set -u -o pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-$REPO/docs/runs/watch_r$(cat "$REPO/tools/BATTERY_ROUND")}"
RUNS="$REPO/docs/runs"
mkdir -p "$OUT" "$RUNS"
cd "$REPO"

stage_done() { [ -f "$OUT/stage.$1.ok" ]; }
mark_done() { touch "$OUT/stage.$1.ok"; }

# One core: pause any background CPU convergence runs (tagged conv_bn /
# sched_ in their command lines) while TPU measurements are
# timing-sensitive.
pkill -STOP -f 'conv_bn|sched_|pytest' 2>/dev/null || true
trap "pkill -CONT -f 'conv_bn|sched_|pytest' 2>/dev/null || true" EXIT

# Re-probe between stages: if the tunnel died mid-battery, return to the
# watcher's poll loop rather than hanging on the next stage.
alive() {
  timeout -k 10 45 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

for extra in "$REPO"/tools/battery.d/*.sh; do
  [ -e "$extra" ] || continue
  name="$(basename "$extra" .sh)"
  if ! stage_done "$name"; then
    alive || { echo "[battery] tunnel died before $name"; exit 0; }
    echo "[battery] stage $name"
    if bash "$extra" "$OUT" 2>&1 | tee "$OUT/$name.log"; then
      mark_done "$name"
    else
      echo "[battery] stage $name failed — will retry next window"
    fi
  fi
done

# Refresh the one-glance artifact roll-up after every battery pass
# (tolerant of pending/torn artifacts by design).
python tools/battery_summary.py >/dev/null 2>&1 || true

# DONE only when every known stage is complete.
all=yes
for extra in "$REPO"/tools/battery.d/*.sh; do
  [ -e "$extra" ] || continue
  stage_done "$(basename "$extra" .sh)" || all=no
done
[ "$all" = yes ] && touch "$OUT/DONE"
exit 0
