#!/usr/bin/env bash
# Measurement battery fired by launch/tpu_watch.sh when the TPU tunnel is
# live. Stages are checkpointed with marker files so a window that closes
# mid-battery resumes where it left off on the next live window instead of
# redoing finished work. Results are archived under docs/runs/.
# pipefail matters: stage results are piped through tee, and without it
# the `if` below tests tee's status — a failed stage would be marked done
# (exactly how the r3 stage-20 OOM slipped through on the first window).
set -u -o pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-$REPO/docs/runs/watch_r3}"
RUNS="$REPO/docs/runs"
mkdir -p "$OUT" "$RUNS"
cd "$REPO"

stage_done() { [ -f "$OUT/stage.$1.ok" ]; }
mark_done() { touch "$OUT/stage.$1.ok"; }

# One core: pause any background CPU convergence runs (tagged conv_bn /
# sched_ in their command lines) while TPU measurements are
# timing-sensitive.
pkill -STOP -f 'conv_bn|sched_' 2>/dev/null || true
trap "pkill -CONT -f 'conv_bn|sched_' 2>/dev/null || true" EXIT

# Re-probe between stages: if the tunnel died mid-battery, return to the
# watcher's poll loop rather than hanging on the next stage.
alive() {
  timeout -k 10 45 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

# -- stage 1: full bench.py (headline artifact) ---------------------------
if ! stage_done bench; then
  echo "[battery] stage bench: python bench.py"
  # The OUTER watcher owns polling: short window, no CPU fallback —
  # if the tunnel died between the watcher's probe and here, return to
  # the poll loop instead of nesting bench.py's own 1h watch inside it.
  BENCH_PROBE_TIMEOUT=60 BENCH_TPU_ATTEMPTS=2 \
  BENCH_WATCH_WINDOW=180 BENCH_CPU_FALLBACK=0 \
    python bench.py >"$OUT/bench.json" 2>"$OUT/bench.stderr"
  rc=$?
  if [ $rc -eq 0 ] && python - "$OUT/bench.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
ok = r.get("backend") == "tpu" and not r.get("partial")
sys.exit(0 if ok else 1)
EOF
  then
    cp "$OUT/bench.json" "$RUNS/bench_r3_tpu_v5e.json"
    cp "$OUT/bench.stderr" "$RUNS/bench_r3_tpu_v5e.log"
    mark_done bench
    echo "[battery] bench complete -> docs/runs/bench_r3_tpu_v5e.json"
  else
    echo "[battery] bench rc=$rc or partial — will retry next window"
    alive || exit 0
  fi
fi

# -- stage 2+: optional extras, added as the round builds them ------------
for extra in "$REPO"/tools/battery.d/*.sh; do
  [ -e "$extra" ] || continue
  name="$(basename "$extra" .sh)"
  if ! stage_done "$name"; then
    alive || { echo "[battery] tunnel died before $name"; exit 0; }
    echo "[battery] stage $name"
    if bash "$extra" "$OUT" 2>&1 | tee "$OUT/$name.log"; then
      mark_done "$name"
    else
      echo "[battery] stage $name failed — will retry next window"
    fi
  fi
done

# DONE only when every known stage is complete.
all=yes
stage_done bench || all=no
for extra in "$REPO"/tools/battery.d/*.sh; do
  [ -e "$extra" ] || continue
  stage_done "$(basename "$extra" .sh)" || all=no
done
[ "$all" = yes ] && touch "$OUT/DONE"
exit 0
