"""Pod-scale compile proof — BASELINE.json config 5 ("ResNet-50 ImageNet,
128-chip pod scaling, replaces 8ps-128wk").

128 real chips don't exist in this environment (one tunneled v5e), so the
honest demonstrable artifact is: the FULL ImageNet ResNet-50 training
step, jitted over a 128-device data-parallel mesh (16 hosts x 8 as the
reference's 128 workers were 16 nodes x 8), lowers and compiles with the
expected ICI collectives — on 128 *virtual* CPU devices, the same
mechanism the driver's dryrun_multichip uses. Where the reference's
8ps-128wk config collapsed to 0.285 st/s behind one parameter server
(reference README.md:49, the SyncReplicas scalability wall README.md:7-12),
the SPMD program has no central party: the gradient all-reduce rides the
mesh.

    python tools/pod_scaling_proof.py [--devices 128] [--out JSON]

Emits: device count, mesh shape, per-device batch, compile wall time,
all-reduce op count + reduced bytes from the compiled HLO.
"""

import argparse
import json
import os
import re
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _inner(n_devices: int, per_device_batch: int, image: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resnet.config import load_config
    from tpu_resnet import parallel
    from tpu_resnet.models import build_model
    from tpu_resnet.train import build_schedule, init_state
    from tpu_resnet.train.step import make_train_step, shard_step

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices

    cfg = load_config("imagenet")
    cfg.data.image_size = image
    cfg.train.global_batch_size = per_device_batch * n_devices
    mesh = parallel.create_mesh(cfg.mesh, devices=devices)

    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    state = init_state(model, cfg.optim, sched, jax.random.PRNGKey(0),
                       jnp.zeros((1, image, image, 3)))
    state = jax.device_put(state, parallel.replicated(mesh))

    bs = parallel.batch_sharding(mesh)
    images = jax.device_put(
        np.zeros((cfg.train.global_batch_size, image, image, 3),
                 np.float32), bs)
    labels = jax.device_put(
        np.zeros((cfg.train.global_batch_size,), np.int32), bs)

    step_fn = shard_step(
        make_train_step(model, cfg.optim, sched, 1000, None,
                        base_rng=jax.random.PRNGKey(1), mesh=mesh),
        mesh, donate_state=False)
    t0 = time.perf_counter()
    lowered = step_fn.lower(state, images, labels)
    lower_secs = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_secs = time.perf_counter() - t0

    hlo = compiled.as_text()
    # Sync and async collective forms (CPU/TPU backends emit either).
    n_ar = len(re.findall(r"all-reduce(?:-start)?\(", hlo))
    n_other = {name: len(re.findall(name + r"(?:-start)?\(", hlo))
               for name in ("all-gather", "reduce-scatter",
                            "collective-permute")}
    out = {
        "devices": n_devices,
        "mesh": dict(mesh.shape),
        "per_device_batch": per_device_batch,
        "global_batch": cfg.train.global_batch_size,
        "image_size": image,
        "model": "imagenet_resnet50_v2 bf16",
        "lower_secs": round(lower_secs, 1),
        "compile_secs": round(compile_secs, 1),
        "all_reduce_ops": n_ar,
        "other_collectives": n_other,
        "hlo_instructions": hlo.count("\n"),
    }
    print("POD_PROOF_JSON: " + json.dumps(out), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=128)
    ap.add_argument("--per-device-batch", type=int, default=8)
    ap.add_argument("--image", type=int, default=64,
                    help="small spatial size keeps the CPU compile fast; "
                    "sharding/collective structure is size-independent")
    ap.add_argument("--out", default="")
    ap.add_argument("--inner", action="store_true")
    args = ap.parse_args()

    if args.inner:
        _inner(args.devices, args.per_device_batch, args.image)
        return 0

    from tpu_resnet.hostenv import run_scrubbed_subprocess

    rc, out = run_scrubbed_subprocess(
        [sys.executable, os.path.abspath(__file__), "--inner",
         "--devices", str(args.devices),
         "--per-device-batch", str(args.per_device_batch),
         "--image", str(args.image)],
        n_devices=args.devices, timeout=1800)
    sys.stdout.write(out)
    if rc != 0:
        print(f"pod proof failed rc={rc}")
        return 1
    for line in reversed(out.splitlines()):
        if line.startswith("POD_PROOF_JSON: "):
            result = json.loads(line[len("POD_PROOF_JSON: "):])
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(result, f, indent=2)
            return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
