#!/usr/bin/env bash
# Sync-BN vs per-replica-BN accuracy delta (VERDICT r2 item 6): the knob
# config.py offers "so the delta can be measured" — measured here on the
# freq100 hard task over the 8-device virtual CPU mesh (per-replica batch
# 128/8 = 16, the regime where the reference observed its distributed
# accuracy gap, reference README.md:36). Single-chip TPU can't show the
# delta (1 device ⇒ the modes coincide), so this runs on CPU; the TPU
# battery SIGSTOPs it while measuring (the box has one core).
#
# Command lines contain "conv_bn" so tools/tpu_battery.sh can pause and
# resume the whole family with pkill -STOP/-CONT -f conv_bn.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
DEST="$REPO/docs/runs/convergence_freq100"
mkdir -p "$DEST"
cd "$REPO"

COMMON="--preset smoke data.synthetic_learnable=true \
  data.synthetic_task=freq100 data.synthetic_classes=100 \
  data.synthetic_label_noise=0.1 data.synthetic_train_examples=8192 \
  data.synthetic_eval_examples=2048 model.resnet_size=8 \
  train.global_batch_size=64 train.train_steps=1200 \
  train.checkpoint_every=500 train.log_every=100 \
  train.eval_batch_size=64 train.image_summary_every=0 \
  optim.schedule=cifar_piecewise optim.boundaries=(600,900,1100) \
  optim.values=(0.1,0.01,0.001,0.0001)"

for mode in sync replica; do
  [ "$mode" = sync ] && flag=true || flag=false
  out="$DEST/bn_$mode"
  if [ -f "$out/best_precision.json" ]; then
    echo "[bn_delta] $mode already done"; continue
  fi
  echo "[bn_delta] arm $mode (sync_bn=$flag) start $(date -u +%T)"
  rm -rf "/tmp/conv_bn_$mode"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    nice -n 19 python -m tpu_resnet train_and_eval $COMMON \
    model.sync_bn=$flag train.train_dir="/tmp/conv_bn_$mode" 2>&1 | tail -4
  mkdir -p "$out"
  cp "/tmp/conv_bn_$mode/metrics.jsonl" "$out/train_metrics.jsonl"
  cp "/tmp/conv_bn_$mode/eval/metrics.jsonl" "$out/eval_metrics.jsonl" \
    2>/dev/null || true
  cp "/tmp/conv_bn_$mode/eval/best_precision.json" "$out/" 2>/dev/null || true
  echo "[bn_delta] arm $mode done $(date -u +%T)"
done

python - "$DEST" <<'EOF'
import json, os, sys
dest = sys.argv[1]
out = {}
for m in ("sync", "replica"):
    p = os.path.join(dest, f"bn_{m}", "best_precision.json")
    if os.path.exists(p):
        out[f"bn_{m}"] = json.load(open(p))
json.dump(out, open(os.path.join(dest, "bn_delta.json"), "w"), indent=2)
print("[bn_delta] summary:", json.dumps(out))
EOF
