"""Integrated fused-block model A/B (VERDICT r3 item 1's second half):
``model.fused_blocks`` on vs off through the REAL headline measurement
path — resident HBM split, on-device augmentation, fused multi-step
dispatch, fetch-synced timing (bench._measure_cifar) — at the CIFAR
ResNet-50 b128 configuration the driver benches. ``--preset imagenet``
runs the same A/B through bench._measure_imagenet (ResNet-50 @224 b128
bf16, FusedBottleneckBlock dispatch) instead.

Battery stage 05 (tools/fused_block_ab.py) decides at the KERNEL level
(isolated block shapes, both directions); this measures what the headline
actually gains end to end, where XLA may already overlap the per-op
overheads the kernel removes. Both numbers together make the
integrate-or-retire decision (docs/PERF.md "CIFAR is overhead-bound":
4.9 ms/step measured vs 1.34 ms byte roofline).

    python tools/fused_model_ab.py --out docs/runs/fused_model_ab_r4.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="cifar10",
                    choices=["cifar10", "imagenet"])
    ap.add_argument("--image", type=int, default=224,
                    help="imagenet only: input resolution")
    ap.add_argument("--warmup-steps", type=int, default=3,
                    help="imagenet only")
    ap.add_argument("--measure-steps", type=int, default=12,
                    help="imagenet only")
    ap.add_argument("--resnet-size", type=int, default=None,
                    help="default: the preset's 50")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--split", type=int, default=50_000)
    ap.add_argument("--steps-per-call", type=int, default=25)
    ap.add_argument("--warmup-chunks", type=int, default=2)
    ap.add_argument("--measure-chunks", type=int, default=6)
    ap.add_argument("--batch-tile", type=int, default=None,
                    help="fused-kernel forward batch tile (cifar only; "
                         "the bottleneck kernels use their own sized "
                         "tile plans)")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.preset == "imagenet" and args.batch_tile is not None:
        # FusedBottleneckBlock has no tile knob (ops-level _DEFAULT_TILES
        # govern) — fail loudly rather than record a tile that was never
        # applied (the repo's conflicting-override convention).
        raise SystemExit("--batch-tile does not apply to --preset "
                         "imagenet (bottleneck tile plans are fixed)")

    import bench
    from tpu_resnet.parallel import create_mesh

    mesh = create_mesh(None)
    plans = [(args.steps_per_call, args.warmup_chunks, args.measure_chunks)]
    arms = {}
    for name, fused in (("xla", False), ("fused", True)):
        def mutate(cfg, fused=fused):
            cfg.model.fused_blocks = fused
            if args.batch_tile is not None:
                cfg.model.fused_block_tile = args.batch_tile
        if args.preset == "imagenet":
            sps, _flops, _comms = bench._measure_imagenet(
                mesh, args.warmup_steps, args.measure_steps,
                resnet_size=args.resnet_size or 50, batch=args.batch,
                image=args.image, mutate_cfg=mutate)
        else:
            sps = bench._measure_cifar(
                mesh, plans, resnet_size=args.resnet_size,
                batch=args.batch, split=args.split,
                mutate_cfg=mutate)[args.steps_per_call]
        arms[name] = sps
        print(f"[fused_model_ab] {name}: {sps:.2f} st/s", flush=True)

    what_cifar = ("model.fused_blocks A/B through the headline resident "
                  "path (fetch-synced, steps_per_call="
                  f"{args.steps_per_call}, b{args.batch})")
    what_imagenet = ("model.fused_blocks A/B through the ImageNet train "
                     f"step (fetch-synced, @{args.image} b{args.batch}, "
                     "FusedBottleneckBlock dispatch)")
    # Ratios from the UNROUNDED rates, with zero guards: a degenerate
    # measurement (0.0 steps/s) must record an artifact, not crash the
    # battery stage with ZeroDivisionError (ADVICE r4).
    out = {
        "what": what_imagenet if args.preset == "imagenet" else what_cifar,
        "preset": args.preset,
        "resnet_size": args.resnet_size or 50,
        "batch": args.batch,
        "steps_per_sec": {k: round(v, 2) for k, v in arms.items()},
        "fused_speedup": (round(arms["fused"] / arms["xla"], 3)
                          if arms["xla"] > 0 else None),
        "fused_wins": arms["fused"] > arms["xla"] > 0,
        "ms_per_step": {k: (round(1000.0 / v, 3) if v > 0 else None)
                        for k, v in arms.items()},
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
