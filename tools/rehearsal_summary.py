"""Shared decay-boundary extraction for the recipe rehearsals (battery
stage 70 on the live chip; tools/recipe_rehearsal_understudy.sh on CPU —
VERDICT r4 item 6). One source so the compressed understudy proves the
exact extraction the full-cadence run will use.

    python tools/rehearsal_summary.py DEST B1 B2 B3 WINDOW [--what TEXT]
                                      [--resume-proven]

Reads DEST/train_metrics.jsonl (+ optional DEST/best_precision.json),
writes DEST/summary.json. For each boundary B the evidence windows are
pre = [B-5*WINDOW, B] and post = [B+WINDOW, B+6*WINDOW] — at the real
cadence (WINDOW=1000, boundaries 40k/60k/80k per reference
resnet_cifar_train.py:302-311) that reproduces the round-3 stage-70
windows exactly.
"""

import argparse
import json
import os
import sys


def summarize(dest, boundaries, window, what, resume_proven=None):
    recs = []
    for line in open(os.path.join(dest, "train_metrics.jsonl")):
        try:  # a mid-write kill at a window close can leave a torn line
            recs.append(json.loads(line))
        except ValueError:
            pass
    recs = [r for r in recs if "loss" in r]

    def win(lo, hi):
        xs = [r["loss"] for r in recs if lo <= r["step"] <= hi]
        return round(sum(xs) / len(xs), 4) if xs else None

    summary = {
        "what": what,
        "steps": recs[-1]["step"] if recs else 0,
        "boundaries": list(boundaries),
        "final_train_precision": recs[-1].get("precision") if recs else None,
    }
    for b in boundaries:
        summary[f"loss_pre_{b}"] = win(b - 5 * window, b)
        summary[f"loss_post_{b}"] = win(b + window, b + 6 * window)
    # The decay signature: loss drops (or at minimum does not rise) across
    # each boundary the run actually reached.
    drops = []
    for b in boundaries:
        pre, post = summary[f"loss_pre_{b}"], summary[f"loss_post_{b}"]
        if pre is not None and post is not None:
            drops.append(post < pre)
    summary["boundaries_reached"] = len(drops)
    summary["loss_dropped_at_each_boundary"] = (all(drops) if drops
                                                else None)
    if resume_proven is not None:
        summary["resume_proven"] = resume_proven
    best = os.path.join(dest, "best_precision.json")
    if os.path.exists(best):
        summary["eval_best"] = json.load(open(best))
    with open(os.path.join(dest, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("dest")
    ap.add_argument("boundaries", nargs=3, type=int)
    ap.add_argument("window", type=int)
    ap.add_argument("--what", default="recipe rehearsal")
    ap.add_argument("--resume-proven", action="store_true", default=None)
    ns = ap.parse_args(argv)
    summary = summarize(ns.dest, ns.boundaries, ns.window, ns.what,
                        ns.resume_proven)
    print("[rehearsal_summary]", json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
