#!/usr/bin/env python3
"""CLI shim for the per-knob sweep harness — see
tpu_resnet/tools/sweep.py (the package module; also reachable as
``python bench.py --sweep`` and ``python -m tpu_resnet.tools.sweep``).

    python tools/sweep.py --space '{"transfer_stage": [1, 8, 16]}'
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_resnet.tools.sweep import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
