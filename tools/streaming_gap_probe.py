"""Isolate the resident-vs-streaming CIFAR step-time gap on a live chip.

Round-2/3 puzzle: the identical chunk program measured 1.7 ms/step fed
from staged streaming superbatches (r2 window) but 4.9 ms/step fed from
the HBM-resident epoch buffer (r2 AND r3 windows, before and after the
carry-slicing unification) — so the carry-slicing theory cannot be the
whole story.  This probe times the same compiled chunk against three
input placements, all transfer-free in the timed loop, so tunnel H2B
bandwidth (the r3 streaming-bench confound) cancels out:

  a. `staged`   — a device_put (stage, B, ...) superbatch, reused every
                  call: the exact streaming program with transfers removed.
  b. `resident` — compile_resident_steps over a DeviceDataset epoch
                  buffer (the bench headline path).
  c. `restage`  — the resident epoch buffer, but each chunk's block is
                  first copied device-to-device into a (stage, B, ...)
                  staging buffer by a tiny jitted slice, then consumed by
                  the same staged program: costs one extra HBM round trip
                  of the block, buys a small/layout-friendly scan input.

If (a) ~ 1.7 ms and (b) ~ 4.9 ms, the epoch buffer's size/layout is the
bottleneck and (c) tells us whether restaging recovers it.  If (a) ~ (b),
the r2 streaming number came from window-to-window chip/tunnel variance.

Usage: python tools/streaming_gap_probe.py [--out out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--stage", type=int, default=8)
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    # Tiny-config overrides so the CPU-mesh test can smoke the exact code
    # the live window runs unattended (tests/test_streaming_gap_probe.py).
    ap.add_argument("--resnet-size", type=int, default=50)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--split", type=int, default=50_000)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from tpu_resnet import parallel
    from tpu_resnet.data import cifar as cifar_data
    from tpu_resnet.data import device_data
    from tpu_resnet.data.augment import get_augment_fns
    from tpu_resnet.parallel import create_mesh
    from tpu_resnet.train.step import make_train_step

    mesh = create_mesh(None)
    stage, reps, warm = args.stage, args.reps, args.warmup
    if warm < 1 or reps < 1:
        raise SystemExit("--warmup and --reps must be >= 1 (the timed "
                         "loop syncs on the warmed metrics)")
    if args.batch < 1 or args.split < 1 or stage < 1:
        raise SystemExit("--batch/--split/--stage must be >= 1")
    if args.split // args.batch < stage:
        raise SystemExit(
            f"--split/--batch = {args.split // args.batch} steps per epoch "
            f"< --stage {stage}: the stage-sized slices would clamp and "
            "silently time overlapping data")
    out = {"device": jax.devices()[0].device_kind, "stage": stage,
           "reps": reps, "resnet_size": args.resnet_size,
           "batch": args.batch, "split": args.split}

    cfg, model, sched, state0, rng = bench._build_train_setup(
        mesh, "cifar10", resnet_size=args.resnet_size, batch=args.batch,
        dtype="bfloat16",
        image=32, synthetic=True)
    batch = cfg.train.global_batch_size
    augment_fn, _ = get_augment_fns("cifar10")
    base_step = make_train_step(model, cfg.optim, sched, 10, augment_fn,
                                base_rng=rng, mesh=mesh)
    run_staged = device_data.compile_staged_stream_steps(base_step, mesh)

    def time_loop(fn, state):
        # Scalar fetch, not block_until_ready: readiness was observed
        # resolving early on a degrading axon tunnel (bench._fetch_sync).
        for _ in range(warm):
            state, m = fn(state)
        bench._fetch_sync(m["loss"])
        t0 = time.perf_counter()
        for _ in range(reps):
            state, m = fn(state)
        bench._fetch_sync(m["loss"])
        dt = time.perf_counter() - t0
        return reps * stage / dt  # steps/sec

    # (a) staged superbatch resident on device, reused every call.
    sharding = parallel.staged_batch_sharding(mesh)
    rng_np = np.random.default_rng(0)
    gi = jax.device_put(
        rng_np.integers(0, 256, (stage, batch, 32, 32, 3), dtype=np.uint8),
        sharding)
    gl = jax.device_put(
        rng_np.integers(0, 10, (stage, batch), dtype=np.int32),
        sharding)
    out["staged_steps_per_sec"] = round(
        time_loop(lambda s: run_staged(s, gi, gl, 0, stage), state0), 2)
    print("staged   :", out["staged_steps_per_sec"], "st/s", flush=True)

    # (b) resident epoch buffer (fresh state — donation consumed state0).
    _, _, _, state1, _ = bench._build_train_setup(
        mesh, "cifar10", resnet_size=args.resnet_size, batch=args.batch,
        dtype="bfloat16",
        image=32, synthetic=True)
    images, labels = cifar_data.synthetic_data(args.split, 32, 10)
    ds = device_data.DeviceDataset(mesh, images, labels, batch, seed=0)
    run_res = device_data.compile_resident_steps(base_step, ds, mesh, stage)
    counter = {"step": 0}

    def res_call(s):
        off = counter["step"] % ds.steps_per_epoch
        if off + stage > ds.steps_per_epoch:
            counter["step"] += ds.steps_per_epoch - off
        s, m = run_res(s, counter["step"], stage)
        counter["step"] += stage
        return s, m

    out["resident_steps_per_sec"] = round(time_loop(res_call, state1), 2)
    print("resident :", out["resident_steps_per_sec"], "st/s", flush=True)

    # (c) restage: device-to-device copy of the chunk block into a small
    # staging buffer, then the same staged program consumes it.
    _, _, _, state2, _ = bench._build_train_setup(
        mesh, "cifar10", resnet_size=args.resnet_size, batch=args.batch,
        dtype="bfloat16",
        image=32, synthetic=True)

    @jax.jit
    def cut(bi, bl, off):
        return (jax.lax.dynamic_slice_in_dim(bi, off, stage, axis=0),
                jax.lax.dynamic_slice_in_dim(bl, off, stage, axis=0))

    counter2 = {"step": 0}

    def restage_call(s):
        off = counter2["step"] % ds.steps_per_epoch
        if off + stage > ds.steps_per_epoch:
            counter2["step"] += ds.steps_per_epoch - off
            off = 0
        ds.ensure_epoch(ds.epoch_of(counter2["step"]))
        si, sl = cut(ds.images, ds.labels, jnp.int32(off))
        s, m = run_staged(s, si, sl, 0, stage)
        counter2["step"] += stage
        return s, m

    out["restage_steps_per_sec"] = round(time_loop(restage_call, state2), 2)
    print("restage  :", out["restage_steps_per_sec"], "st/s", flush=True)

    print(json.dumps(out))
    if args.out:
        json.dump(out, open(args.out, "w"), indent=2)


if __name__ == "__main__":
    main()
