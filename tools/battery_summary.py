"""Collect the per-round measurement artifacts into one summary table —
what landed, what's pending, and the headline numbers, so a glance at
``python tools/battery_summary.py`` (or the committed
docs/runs/summary_r<N>.json) answers "what did the live windows produce"
without spelunking a dozen JSONs.

Tolerant by design: every artifact is optional (the tunnel decides what
lands), torn files read as status=unreadable, and the decisive A/B
verdicts are computed with the same speedup>1 rule the gated battery
stages use.
"""

import glob
import json
import re
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import ab_gate  # noqa: E402  (shared A/B win rule)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNS = os.path.join(REPO, "docs", "runs")
# Single source for the round tag (tools/BATTERY_ROUND) — the battery
# stages, watcher defaults, and this summary all derive from it, so a
# round bump is a one-file edit instead of a 13-file sed.
CURRENT_ROUND = int(open(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "BATTERY_ROUND")).read().strip())


def _load(name):
    """Load the newest READABLE round of an artifact: ``name`` may embed a
    round tag (``_r4``), which is generalized to ``_r*``; rounds are tried
    newest-first and a torn newest file (e.g. a stage mid-write while the
    battery's post-pass summary runs) falls back to the next round's
    readable truth instead of hiding it (review finding r5)."""
    pat = re.sub(r"_r\d+", "_r*", name)
    cands = []
    for p in glob.glob(os.path.join(RUNS, pat)):
        m = re.search(r"_r(\d+)", os.path.basename(os.path.dirname(p))
                      if os.path.basename(p) == "summary.json"
                      else os.path.basename(p))
        cands.append((int(m.group(1)) if m else 0, p))
    if not cands:
        return None, "pending"
    errs = []
    for _, path in sorted(cands, reverse=True):
        try:
            with open(path) as f:
                return json.load(f), f"ok ({os.path.relpath(path, RUNS)})"
        except (ValueError, OSError) as e:
            errs.append(f"{os.path.relpath(path, RUNS)}: {e}")
    return None, "unreadable: " + "; ".join(errs)


def _ab_verdict(art):
    """Per-direction best speedup across shapes + the gated-stage rule
    (win threshold shared with the stage gates via tools/ab_gate.py)."""
    if not art:
        return None
    dirs = {}
    for shape in art.get("by_shape", {}).values():
        for name, d in shape.items():
            if isinstance(d, dict) and "speedup" in d:
                dirs.setdefault(name, []).append(d["speedup"])
    if not dirs:
        return {"any_win": None, "note": "no measured directions"}
    return {
        "best_speedup_by_direction": {k: max(v) for k, v in dirs.items()},
        "any_win": any(s > ab_gate.WIN_THRESHOLD
                       for v in dirs.values() for s in v),
    }


def main() -> int:
    out = {}

    bench, st = _load("bench_r4_tpu_v5e.json")
    out["bench"] = {"status": st}
    if bench:
        out["bench"].update({
            "cifar_steps_per_sec": bench.get("value"),
            "vs_baseline": bench.get("vs_baseline"),
            "imagenet": bench.get("imagenet"),
        })

    for name, key in (("fused_block_ab_r4.json", "fused_block_kernel_ab"),
                      ("fused_bottleneck_ab_r4.json",
                       "fused_bottleneck_kernel_ab")):
        art, st = _load(name)
        out[key] = {"status": st}
        v = _ab_verdict(art)
        if v:
            out[key].update(v)

    for name, key in (("fused_model_ab_r4.json", "fused_model_cifar_ab"),
                      ("fused_model_imagenet_ab_r4.json",
                       "fused_model_imagenet_ab")):
        art, st = _load(name)
        out[key] = {"status": st}
        if art:
            out[key].update({
                "steps_per_sec": art.get("steps_per_sec"),
                "fused_speedup": art.get("fused_speedup"),
                "fused_wins": art.get("fused_wins"),
            })

    art, st = _load("cifar_cost_r4.json")
    out["cifar_roofline"] = {"status": st}
    if art:
        out["cifar_roofline"].update({
            "steps_per_sec": art.get("steps_per_sec"),
            "mfu": art.get("mfu"),
        })

    art, st = _load("sweeps_r4.json")
    out["sweeps"] = {"status": st}
    if art:
        out["sweeps"].update(art)

    art, st = _load("streaming_gap_r4.json")
    out["streaming_gap"] = {"status": st}
    if art:
        out["streaming_gap"].update(
            {k: art[k] for k in art if k.endswith("steps_per_sec")})

    for b in (128, 256):
        art, st = _load(f"mfu_b{b}_r4.json")
        out[f"imagenet_mfu_b{b}"] = {"status": st}
        if art:
            out[f"imagenet_mfu_b{b}"].update({
                "steps_per_sec": art.get("steps_per_sec"),
                "mfu": art.get("mfu"),
            })

    art, st = _load("imagenet_stream_r4.json")
    out["imagenet_streaming"] = {"status": st}
    if art:
        out["imagenet_streaming"].update({
            "sustained_steps_per_sec": art.get("sustained_steps_per_sec"),
            "images_per_sec": art.get("images_per_sec"),
        })

    for name, key in (("fused_imagenet_basic_ab_r4.json",
                       "fused_imagenet_basic_ab"),):
        art, st = _load(name)
        out[key] = {"status": st}
        if art:
            out[key].update({
                "steps_per_sec": art.get("steps_per_sec"),
                "fused_speedup": art.get("fused_speedup"),
                "fused_wins": art.get("fused_wins"),
            })

    for fam in ("block", "bottleneck"):
        art, st = _load(f"compile_smoke_{fam}_r4.json")
        out[f"compile_smoke_{fam}"] = {"status": st}
        if art:
            out[f"compile_smoke_{fam}"].update({
                "compile_ok": art.get("compile_ok"),
                "checks": art.get("checks"),
            })

    art, st = _load("fused_shardmap_smoke_r4.json")
    out["fused_shardmap_smoke"] = {"status": st}
    if art:
        out["fused_shardmap_smoke"].update({
            "ok": art.get("ok"), "abs_diff": art.get("abs_diff")})

    art, st = _load(os.path.join("recipe_rehearsal_r4", "summary.json"))
    out["recipe_rehearsal"] = {"status": st}
    if art:
        out["recipe_rehearsal"].update(art)

    art, st = _load(os.path.join("recipe_rehearsal_cpu_r4", "summary.json"))
    out["recipe_rehearsal_cpu_understudy"] = {"status": st}
    if art:
        out["recipe_rehearsal_cpu_understudy"].update({
            k: art.get(k) for k in
            ("steps", "resume_proven", "loss_dropped_at_each_boundary",
             "boundaries_reached", "eval_best")})

    art, st = _load("input_scaling_r4.json")
    out["input_scaling"] = {"status": st}
    if art:
        out["input_scaling"].update({
            "scaling_curve_native": art.get("scaling_curve_native"),
            "cores_needed_per_chip": art.get("cores_needed_per_chip"),
            "cores_needed_assumes": art.get("cores_needed_assumes"),
        })

    art, st = _load("multihost_2proc_r4.json")
    out["multihost_2proc"] = {"status": st}
    if art:
        out["multihost_2proc"].update({
            "spmd_identical": art.get("spmd_identical"),
            "topology": art.get("topology"),
        })

    # Two counts, deliberately distinct (review finding r5): the
    # cross-round fallback means "landed" includes prior-round truth, so
    # it must not read as this round's production.
    statuses = [str(v.get("status", "")) for v in out.values()]
    landed = sum(1 for s in statuses if s.startswith("ok"))
    cur = sum(1 for s in statuses
              if s.startswith("ok") and f"_r{CURRENT_ROUND}" in s)
    out["_meta"] = {
        "artifacts_landed_any_round": landed,
        "artifacts_landed_current_round": cur,
        "current_round": CURRENT_ROUND,
        "artifacts_total": len(out),
    }
    print(json.dumps(out, indent=2))
    dest = os.path.join(RUNS, f"summary_r{CURRENT_ROUND}.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
