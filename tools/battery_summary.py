"""Collect the round-4 measurement artifacts into one summary table —
what landed, what's pending, and the headline numbers, so a glance at
``python tools/battery_summary.py`` (or the committed
docs/runs/summary_r4.json) answers "what did the live windows produce"
without spelunking a dozen JSONs.

Tolerant by design: every artifact is optional (the tunnel decides what
lands), torn files read as status=unreadable, and the decisive A/B
verdicts are computed with the same speedup>1 rule the gated battery
stages use.
"""

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNS = os.path.join(REPO, "docs", "runs")


def _load(name):
    path = os.path.join(RUNS, name)
    if not os.path.exists(path):
        return None, "pending"
    try:
        with open(path) as f:
            return json.load(f), "ok"
    except (ValueError, OSError) as e:
        return None, f"unreadable: {e}"


def _ab_verdict(art):
    """Per-direction best speedup across shapes + the gated-stage rule."""
    if not art:
        return None
    dirs = {}
    for shape in art.get("by_shape", {}).values():
        for name, d in shape.items():
            if isinstance(d, dict) and "speedup" in d:
                dirs.setdefault(name, []).append(d["speedup"])
    if not dirs:
        return {"any_win": None, "note": "no measured directions"}
    return {
        "best_speedup_by_direction": {k: max(v) for k, v in dirs.items()},
        "any_win": any(s > 1.0 for v in dirs.values() for s in v),
    }


def main() -> int:
    out = {}

    bench, st = _load("bench_r4_tpu_v5e.json")
    out["bench"] = {"status": st}
    if bench:
        out["bench"].update({
            "cifar_steps_per_sec": bench.get("value"),
            "vs_baseline": bench.get("vs_baseline"),
            "imagenet": bench.get("imagenet"),
        })

    for name, key in (("fused_block_ab_r4.json", "fused_block_kernel_ab"),
                      ("fused_bottleneck_ab_r4.json",
                       "fused_bottleneck_kernel_ab")):
        art, st = _load(name)
        out[key] = {"status": st}
        v = _ab_verdict(art)
        if v:
            out[key].update(v)

    for name, key in (("fused_model_ab_r4.json", "fused_model_cifar_ab"),
                      ("fused_model_imagenet_ab_r4.json",
                       "fused_model_imagenet_ab")):
        art, st = _load(name)
        out[key] = {"status": st}
        if art:
            out[key].update({
                "steps_per_sec": art.get("steps_per_sec"),
                "fused_speedup": art.get("fused_speedup"),
                "fused_wins": art.get("fused_wins"),
            })

    art, st = _load("cifar_cost_r4.json")
    out["cifar_roofline"] = {"status": st}
    if art:
        out["cifar_roofline"].update({
            "steps_per_sec": art.get("steps_per_sec"),
            "mfu": art.get("mfu"),
        })

    art, st = _load("sweeps_r4.json")
    out["sweeps"] = {"status": st}
    if art:
        out["sweeps"].update(art)

    art, st = _load("streaming_gap_r4.json")
    out["streaming_gap"] = {"status": st}
    if art:
        out["streaming_gap"].update(
            {k: art[k] for k in art if k.endswith("steps_per_sec")})

    for b in (128, 256):
        art, st = _load(f"mfu_b{b}_r4.json")
        out[f"imagenet_mfu_b{b}"] = {"status": st}
        if art:
            out[f"imagenet_mfu_b{b}"].update({
                "steps_per_sec": art.get("steps_per_sec"),
                "mfu": art.get("mfu"),
            })

    art, st = _load("imagenet_stream_r4.json")
    out["imagenet_streaming"] = {"status": st}
    if art:
        out["imagenet_streaming"].update({
            "sustained_steps_per_sec": art.get("sustained_steps_per_sec"),
            "images_per_sec": art.get("images_per_sec"),
        })

    art, st = _load(os.path.join("recipe_rehearsal_r4", "summary.json"))
    out["recipe_rehearsal"] = {"status": st}
    if art:
        out["recipe_rehearsal"].update(art)

    art, st = _load("multihost_2proc_r4.json")
    out["multihost_2proc"] = {"status": st}
    if art:
        out["multihost_2proc"].update({
            "spmd_identical": art.get("spmd_identical"),
            "topology": art.get("topology"),
        })

    landed = sum(1 for v in out.values() if v.get("status") == "ok")
    out["_meta"] = {"artifacts_landed": landed, "artifacts_total": len(out)}
    print(json.dumps(out, indent=2))
    dest = os.path.join(RUNS, "summary_r4.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
