"""Driver-visible multihost artifact (VERDICT r3 item 7): a real
``jax.distributed`` 2-process x 4-virtual-device data-parallel training
run, archived with per-process loss series — the multi-process path
promoted out of pytest (tests/test_multihost.py) into a standalone probe
whose JSON the judge can read without running the suite.

The reference's only multi-node rehearsal was a localhost fake cluster of
OS processes over local ports (mkl-scripts/submit_mac_dist.sh); this is
the TPU-native analog: two OS processes rendezvous through
``jax.distributed.initialize`` via the launcher env protocol
(TPU_COORDINATOR_ADDRESS/TPU_NUM_PROCESSES/TPU_PROCESS_ID), each owning 4
virtual CPU devices, and run the real train step over the 8-device global
mesh — per-process input striping, global-batch assembly, cross-process
gradient allreduce. SPMD check: every process must record the identical
global loss at every step.

    python tools/multihost_probe.py --steps 12 --out docs/runs/multihost_2proc_r4.json
"""

import argparse
import json
import os
import socket
import subprocess
import tempfile
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")

from tpu_resnet import parallel

parallel.initialize()  # from TPU_* env vars (launcher protocol)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

import jax.numpy as jnp
import numpy as np
from tpu_resnet.config import load_config
from tpu_resnet.data import pipeline
from tpu_resnet.data.cifar import synthetic_data
from tpu_resnet.models import build_model
from tpu_resnet.train import build_schedule, init_state
from tpu_resnet.train.step import make_train_step, shard_step

steps = int(os.environ["MULTIHOST_PROBE_STEPS"])
cfg = load_config("smoke")
cfg.train.global_batch_size = 32
mesh = parallel.create_mesh(cfg.mesh)
model = build_model(cfg)
sched = build_schedule(cfg.optim, cfg.train)
state = init_state(model, cfg.optim, sched, jax.random.PRNGKey(0),
                   jnp.zeros((1, 32, 32, 3)))
state = jax.device_put(state, parallel.replicated(mesh))
step_fn = shard_step(
    make_train_step(model, cfg.optim, sched, 10, augment_fn=None,
                    base_rng=jax.random.PRNGKey(1)), mesh)

images, labels = synthetic_data(256, 32, 10, seed=0)
local_bs = parallel.local_batch_size(cfg.train.global_batch_size, mesh)
batcher = pipeline.ShardedBatcher(images, labels.astype(np.int32), local_bs,
                                  seed=0)
it = pipeline.device_prefetch(iter(batcher), parallel.batch_sharding(mesh))
losses = []
for i in range(steps):
    gi, gl = next(it)
    assert gi.shape[0] == cfg.train.global_batch_size
    state, metrics = step_fn(state, gi, gl)
    losses.append(float(jax.device_get(metrics["loss"])))
print("PROBE_JSON: " + json.dumps({
    "process": jax.process_index(),
    "process_count": jax.process_count(),
    "global_devices": jax.device_count(),
    "local_devices": jax.local_device_count(),
    "local_batch": local_bs,
    "final_step": int(jax.device_get(state.step)),
    "losses": losses,
}))
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--out", default="docs/runs/multihost_2proc_r4.json")
    ap.add_argument("--timeout", type=int, default=560)
    args = ap.parse_args()

    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()

    t0 = time.time()
    procs = []
    outfiles = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # force CPU backend
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["TPU_COORDINATOR_ADDRESS"] = coord
        env["TPU_NUM_PROCESSES"] = "2"
        env["TPU_PROCESS_ID"] = str(pid)
        env["MULTIHOST_PROBE_STEPS"] = str(args.steps)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # File-backed capture, not PIPE: the parent waits on the workers
        # sequentially, and an undrained pipe that fills (warning storms)
        # would block one worker's write(2) mid-collective and deadlock
        # BOTH until the timeout.
        f = tempfile.TemporaryFile(mode="w+")
        outfiles.append(f)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER], env=env, cwd=REPO,
            stdout=f, stderr=subprocess.STDOUT, text=True))

    results = []
    try:
        deadline = time.time() + args.timeout
        for p, f in zip(procs, outfiles):
            p.wait(timeout=max(1.0, deadline - time.time()))
            f.seek(0)
            out = f.read()
            if p.returncode != 0:
                sys.stderr.write(out[-3000:])
                raise SystemExit(f"worker rc={p.returncode}")
            line = next(l for l in reversed(out.splitlines())
                        if l.startswith("PROBE_JSON: "))
            results.append(json.loads(line[len("PROBE_JSON: "):]))
    finally:  # never leak the sibling worker when one fails
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in outfiles:
            f.close()

    by_pid = {r["process"]: r for r in results}
    assert set(by_pid) == {0, 1}, by_pid.keys()
    # SPMD contract: identical global loss on every process at every step.
    max_dev = max(abs(a - b) for a, b in
                  zip(by_pid[0]["losses"], by_pid[1]["losses"]))
    assert max_dev < 1e-6, f"processes diverged: max |delta|={max_dev}"
    assert all(r["final_step"] == args.steps for r in results)

    artifact = {
        "what": ("real jax.distributed 2-process x 4-virtual-CPU-device "
                 "data-parallel training (launcher env protocol, "
                 "per-process input striping, cross-process gradient "
                 "allreduce) — tests/test_multihost.py promoted to a "
                 "standalone artifact"),
        "topology": {"processes": 2, "devices_per_process": 4,
                     "global_devices": 8,
                     "global_batch": 32,
                     "local_batch": by_pid[0]["local_batch"]},
        "steps": args.steps,
        "loss_by_process": {str(pid): r["losses"]
                            for pid, r in sorted(by_pid.items())},
        "max_cross_process_loss_delta": max_dev,
        "spmd_identical": True,
        "wall_seconds": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(os.path.join(REPO, args.out)), exist_ok=True)
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(artifact, f, indent=2)
    print(json.dumps({k: artifact[k] for k in
                      ("topology", "steps", "max_cross_process_loss_delta",
                       "spmd_identical", "wall_seconds")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
