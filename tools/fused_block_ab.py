"""A/B the fused Pallas basic-block against XLA's compilation of the
identical math, at the CIFAR ResNet's three stage shapes (the decisive
experiment for docs/PERF.md's "CIFAR is overhead-bound" hypothesis — see
ops/fused_block.py).

Each arm chains L sequential block applications inside ONE lax.scan
dispatch (per-dispatch tunnel latency cannot mask per-block costs), with
chained inputs so XLA can neither hoist nor overlap iterations. The
fwd_bwd arms differentiate wrt the input AND every parameter so both
sides compute the full gradient set (params closed over would let XLA
dead-code-eliminate its wgrad work while the opaque Pallas kernel still
pays for it). Timing is fetch-synced (bench._fetch_sync); the output
JSON is rewritten after every shape so a mid-run tunnel death preserves
the shapes already measured.

    python tools/fused_block_ab.py [--out JSON] [--length 32] [--reps 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (batch, spatial, channels, fwd_tile, bwd_tile): the three CIFAR-ResNet
# stage shapes (models/resnet.py cifar_resnet_v2 — 16@32x32, 32@16x16,
# 64@8x8). Tiles sized for ~16 MB core VMEM: the fwd kernel keeps ~6
# tile-sized fp32 buffers live, the bwd kernel ~12.
SHAPES = [(128, 32, 32, 16, 16, 8), (128, 16, 16, 32, 32, 16),
          (128, 8, 8, 64, 64, 32)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--length", type=int, default=32,
                    help="blocks chained per dispatch")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    args = ap.parse_args()
    if args.length < 1 or args.reps < 1:
        raise SystemExit("--length and --reps must be >= 1")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from tpu_resnet.ops.fused_block import (block_apply, block_fwd,
                                            block_fwd_reference,
                                            block_train_apply,
                                            block_train_fwd,
                                            block_train_fwd_reference)

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    out = {"device": jax.devices()[0].device_kind, "length": args.length,
           "dtype": args.dtype, "by_shape": {}}

    def flush():
        if args.out:
            json.dump(out, open(args.out, "w"), indent=2)

    for b, h, w, c, bt_fwd, bt_bwd in SHAPES:
        key = f"b{b}_{h}x{w}x{c}"
        try:
            rng = np.random.default_rng(c)
            x0 = jnp.asarray(rng.normal(size=(b, h, w, c)), dtype)
            # Tiny weights: 32 chained residual blocks must stay finite.
            params = (
                jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.01, dtype),
                jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.01, dtype),
                jnp.ones((c,), dtype), jnp.zeros((c,), dtype),
                jnp.ones((c,), dtype), jnp.zeros((c,), dtype))

            def chained(block):
                @jax.jit
                def run(x):
                    def body(xc, _):
                        return block(xc, *params), None
                    xc, _ = jax.lax.scan(body, x, None, length=args.length)
                    return jnp.float32(jnp.sum(xc))
                return run

            def chained_grad(block, block_params, tuple_out=False):
                # Params are loss ARGUMENTS (argnums 0..6): both arms must
                # compute dx and all six parameter grads. tuple_out: the
                # live-BN blocks return (y, moments); moments are unused
                # (stop-gradient EMA convention).
                def loss(x, *p):
                    def body(xc, _):
                        y = block(xc, *p)
                        return (y[0] if tuple_out else y), None
                    xc, _ = jax.lax.scan(body, x, None, length=args.length)
                    return jnp.float32(jnp.sum(xc))

                g = jax.grad(loss, argnums=tuple(range(1 + len(block_params))))

                @jax.jit
                def run(x):
                    grads = g(x, *block_params)
                    return sum(jnp.float32(jnp.sum(gr)) for gr in grads)
                return run

            def time_arm(run):
                bench._fetch_sync(run(x0))  # compile + warm
                best = float("inf")
                for _ in range(args.reps):
                    t0 = time.perf_counter()
                    bench._fetch_sync(run(x0))
                    best = min(best, time.perf_counter() - t0)
                return best / args.length * 1e6  # us per block

            entry = {}
            pallas_us = time_arm(chained(
                lambda x, *p: block_fwd(x, *p, batch_tile=bt_fwd)))
            xla_us = time_arm(chained(block_fwd_reference))
            entry["fwd"] = {
                "pallas_us_per_block": round(pallas_us, 2),
                "xla_us_per_block": round(xla_us, 2),
                "speedup": round(xla_us / pallas_us, 3)}
            out["by_shape"][key] = entry
            flush()  # fwd numbers survive a bwd failure

            pallas_g_us = time_arm(chained_grad(
                lambda x, *p: block_apply(x, *p, bt_fwd, None, bt_bwd),
                params))
            xla_g_us = time_arm(chained_grad(block_fwd_reference, params))
            entry["fwd_bwd"] = {
                "pallas_us_per_block": round(pallas_g_us, 2),
                "xla_us_per_block": round(xla_g_us, 2),
                "speedup": round(xla_g_us / pallas_g_us, 3)}
            flush()

            # Training forward with LIVE batch stats (two-pass: stats
            # kernel + folded apply) — does the stats pass eat the win?
            gb = (jnp.ones((c,), dtype), jnp.zeros((c,), dtype),
                  jnp.ones((c,), dtype), jnp.zeros((c,), dtype))
            w12 = params[:2]

            def chained_train(block):
                @jax.jit
                def run(x):
                    def body(xc, _):
                        y, _moms = block(xc, *w12, *gb)
                        return y, None
                    xc, _ = jax.lax.scan(body, x, None, length=args.length)
                    return jnp.float32(jnp.sum(xc))
                return run

            pallas_t_us = time_arm(chained_train(
                lambda x, *p: block_train_fwd(x, *p, batch_tile=bt_fwd)))
            xla_t_us = time_arm(chained_train(block_train_fwd_reference))
            entry["train_fwd_live_bn"] = {
                "pallas_us_per_block": round(pallas_t_us, 2),
                "xla_us_per_block": round(xla_t_us, 2),
                "speedup": round(xla_t_us / pallas_t_us, 3)}
            flush()

            # The end-to-end training direction: fwd+bwd with live BN —
            # the number that decides model integration.
            train_params = (*w12, *gb)
            pallas_tg_us = time_arm(chained_grad(
                lambda x, *p: block_train_apply(
                    x, *p, 1e-5, bt_fwd, None),
                train_params, tuple_out=True))
            xla_tg_us = time_arm(chained_grad(
                block_train_fwd_reference, train_params, tuple_out=True))
            entry["train_fwd_bwd_live_bn"] = {
                "pallas_us_per_block": round(pallas_tg_us, 2),
                "xla_us_per_block": round(xla_tg_us, 2),
                "speedup": round(xla_tg_us / pallas_tg_us, 3)}
        except Exception as e:  # record and keep measuring other shapes
            out["by_shape"].setdefault(key, {})["error"] = (
                f"{type(e).__name__}: {e}"[:500])
            traceback.print_exc()
        print(key, out["by_shape"][key], flush=True)
        flush()

    print(json.dumps(out))
    flush()


if __name__ == "__main__":
    main()
