"""A/B the fused Pallas basic-block forward against XLA's compilation of
the identical math, at the CIFAR ResNet's three stage shapes (the
decisive experiment for docs/PERF.md's "CIFAR is overhead-bound"
hypothesis — see ops/fused_block.py).

Each arm chains L sequential block applications inside ONE lax.scan
dispatch (per-dispatch tunnel latency cannot mask per-block costs), with
chained inputs so XLA can neither hoist nor overlap iterations. Timing
is fetch-synced (bench._fetch_sync).

    python tools/fused_block_ab.py [--out JSON] [--length 32] [--reps 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (batch, spatial, channels, batch_tile): the three CIFAR-ResNet stage
# shapes (models/resnet.py cifar_resnet_v2 — 16@32x32, 32@16x16, 64@8x8).
SHAPES = [(128, 32, 32, 16, 16), (128, 16, 16, 32, 32),
          (128, 8, 8, 64, 128)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--length", type=int, default=32,
                    help="blocks chained per dispatch")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    args = ap.parse_args()
    if args.length < 1 or args.reps < 1:
        raise SystemExit("--length and --reps must be >= 1")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from tpu_resnet.ops.fused_block import block_fwd, block_fwd_reference

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    out = {"device": jax.devices()[0].device_kind, "length": args.length,
           "dtype": args.dtype, "by_shape": {}}

    for b, h, w, c, bt in SHAPES:
        rng = np.random.default_rng(c)
        x0 = jnp.asarray(rng.normal(size=(b, h, w, c)), dtype)
        # Tiny weights: 32 chained residual blocks must stay finite.
        params = (
            jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.01, dtype),
            jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.01, dtype),
            jnp.ones((c,), dtype), jnp.zeros((c,), dtype),
            jnp.ones((c,), dtype), jnp.zeros((c,), dtype))

        def chained(block):
            @jax.jit
            def run(x):
                def body(xc, _):
                    return block(xc, *params), None
                xc, _ = jax.lax.scan(body, x, None, length=args.length)
                return jnp.float32(jnp.sum(xc))
            return run

        def time_arm(run):
            bench._fetch_sync(run(x0))  # compile + warm
            best = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                bench._fetch_sync(run(x0))
                best = min(best, time.perf_counter() - t0)
            return best / args.length * 1e6  # us per block

        pallas_us = time_arm(chained(
            lambda x, *p: block_fwd(x, *p, batch_tile=bt)))
        xla_us = time_arm(chained(block_fwd_reference))
        key = f"b{b}_{h}x{w}x{c}"
        out["by_shape"][key] = {
            "pallas_us_per_block": round(pallas_us, 2),
            "xla_us_per_block": round(xla_us, 2),
            "speedup": round(xla_us / pallas_us, 3)}
        print(key, out["by_shape"][key], flush=True)

    print(json.dumps(out))
    if args.out:
        json.dump(out, open(args.out, "w"), indent=2)


if __name__ == "__main__":
    main()
