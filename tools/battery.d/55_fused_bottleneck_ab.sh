#!/usr/bin/env bash
# Halo-tiled fused ImageNet bottleneck A/B (VERDICT r3 item 4) — GATED on
# the basic-block kernel A/B having proven block fusion on this chip: if
# stage 05's artifact shows no direction with speedup > 1, skip (exit 0,
# stage marked done) per "on a loss, stop investing in Pallas block
# fusion". A gate PARSE error is NOT a negative result: it fails the
# stage so the battery retries next window instead of silently marking
# a crashed evaluation as a standing loss.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

# FUSED_AB_GATE override exists for tests (they must not depend on live
# repo artifact state, nor risk launching the real 2700s A/B).
GATE="${FUSED_AB_GATE:-docs/runs/fused_block_ab_r${RND}.json}"
if [ ! -f "$GATE" ]; then
  # A missing gate is NOT a negative result either: stage 05 may simply
  # have crashed/timed out this window and will retry. Fail the stage so
  # it stays armed; only a measured loss (below) marks it done.
  echo "[fused_bottleneck_ab] gate artifact $GATE missing (stage 05 not run?) — will retry next window"
  exit 1
fi
# Shared rule (tools/ab_gate.py): 0=win, 1=measured loss, 2=torn artifact.
python tools/ab_gate.py "$GATE"
rc=$?
if [ $rc -eq 1 ]; then
  echo "[fused_bottleneck_ab] basic-block A/B shows no winning direction — skipping (negative result stands)"
  exit 0
elif [ $rc -eq 2 ]; then
  echo "[fused_bottleneck_ab] gate evaluation failed — stage will retry next window"
  exit 1
fi

# Compile-smoke prelude — same rationale and error discipline as stage
# 05's (see 05_fused_block_ab.sh): fail in ~1 min, not mid-A/B.
# SMOKE/AB_OUT overridable + COMPILE_SMOKE_FORCE=fail|timeout: the skip
# logic is CPU-testable (tests/test_compile_smoke.py) without touching
# live artifacts or running a real compile.
SMOKE="${COMPILE_SMOKE_OUT:-docs/runs/compile_smoke_bottleneck_r${RND}.json}"
AB_OUT="${FUSED_BOTTLENECK_AB_OUT:-docs/runs/fused_bottleneck_ab_r${RND}.json}"
case "${COMPILE_SMOKE_FORCE:-}" in
  fail)
    printf '{"compile_ok": false, "error": "forced by test", "by_shape": {}}' > "$SMOKE"
    src=1 ;;
  timeout)
    src=124 ;;
  *)
    timeout -k 15 300 python tools/pallas_compile_smoke.py \
      --family bottleneck --out "$SMOKE"
    src=$? ;;
esac
if [ $src -eq 124 ] || [ $src -eq 137 ]; then
  echo "[fused_bottleneck_ab] compile smoke timed out (tunnel flake?) — will retry next window"
  exit 1
elif [ $src -ne 0 ]; then
  cp "$SMOKE" "$AB_OUT"
  echo "[fused_bottleneck_ab] non-interpret compile FAILED — A/B skipped, error archived"
  exit 0
fi
echo "[fused_bottleneck_ab] compile smoke OK — running the A/B"

# 2 arms x 4 directions x 3 shapes (24 scan-program compiles); compiles
# dominate first-cache runs.
timeout -k 30 2700 python tools/fused_bottleneck_ab.py \
  --out "$AB_OUT" | tail -6
