#!/usr/bin/env bash
# Halo-tiled fused ImageNet bottleneck A/B (VERDICT r3 item 4) — GATED on
# the basic-block kernel A/B having proven block fusion on this chip: if
# stage 05's artifact shows no direction with speedup > 1, skip (exit 0,
# stage marked done) per "on a loss, stop investing in Pallas block
# fusion". A gate PARSE error is NOT a negative result: it fails the
# stage so the battery retries next window instead of silently marking
# a crashed evaluation as a standing loss.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
cd "$REPO"

GATE="docs/runs/fused_block_ab_r4.json"
if [ ! -f "$GATE" ]; then
  echo "[fused_bottleneck_ab] gate artifact $GATE missing (stage 05 not run?) — skipping"
  exit 0
fi
python - "$GATE" <<'EOF'
import json, sys
try:
    r = json.load(open(sys.argv[1]))
    wins = [d.get("speedup", 0) > 1.0
            for shape in r.get("by_shape", {}).values()
            for name, d in shape.items() if isinstance(d, dict)]
except Exception as e:  # torn/invalid artifact: infra error, not a loss
    print(f"[fused_bottleneck_ab] gate artifact unreadable: {e}")
    sys.exit(2)
if not wins:
    print("[fused_bottleneck_ab] gate artifact has no measured directions")
    sys.exit(2)
sys.exit(0 if any(wins) else 1)
EOF
rc=$?
if [ $rc -eq 1 ]; then
  echo "[fused_bottleneck_ab] basic-block A/B shows no winning direction — skipping (negative result stands)"
  exit 0
elif [ $rc -eq 2 ]; then
  echo "[fused_bottleneck_ab] gate evaluation failed — stage will retry next window"
  exit 1
fi

# 2 arms x 4 directions x 3 shapes (24 scan-program compiles); compiles
# dominate first-cache runs.
timeout -k 30 2700 python tools/fused_bottleneck_ab.py \
  --out docs/runs/fused_bottleneck_ab_r4.json | tail -6
