#!/usr/bin/env bash
# Convergence evidence on the live TPU (VERDICT r2 item 6): the hard
# freq100 synthetic task (100 classes, random phase, 10% train label
# noise — eval clean) run long enough that the compressed piecewise LR
# schedule visibly matters, plus the constant-LR ablation. Full stack:
# train loop + on-device augmentation + checkpointing + eval sidecar.
# The sync-vs-per-replica-BN delta runs on the 8-device CPU mesh instead
# (single-chip TPU has one device, so the BN modes coincide there) — see
# tools/convergence_bn_delta.sh.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
OUT="${1:-$REPO/docs/runs/watch_r3}"
DEST="$REPO/docs/runs/convergence_freq100"
mkdir -p "$DEST"
cd "$REPO"

COMMON="--preset smoke data.synthetic_learnable=true \
  data.synthetic_task=freq100 data.synthetic_classes=100 \
  data.synthetic_label_noise=0.1 data.synthetic_train_examples=20480 \
  data.synthetic_eval_examples=2048 model.resnet_size=20 \
  model.compute_dtype=bfloat16 train.global_batch_size=128 \
  train.train_steps=6000 train.checkpoint_every=500 train.log_every=100 \
  train.eval_batch_size=128 train.image_summary_every=0"

run_arm () {
  name="$1"; shift
  echo "[convergence] arm $name"
  rm -rf "/tmp/conv_$name"
  timeout -k 30 1500 python -m tpu_resnet train_and_eval $COMMON \
    train.train_dir="/tmp/conv_$name" "$@" 2>&1 | tail -5
  mkdir -p "$DEST/$name"
  cp "/tmp/conv_$name/metrics.jsonl" "$DEST/$name/train_metrics.jsonl"
  cp "/tmp/conv_$name/eval/metrics.jsonl" "$DEST/$name/eval_metrics.jsonl" \
    2>/dev/null || true
  cp "/tmp/conv_$name/eval/best_precision.json" "$DEST/$name/" \
    2>/dev/null || true
  python -m tpu_resnet plot --dir "/tmp/conv_$name" \
    --out "$DEST/$name/curves.png" --csv "$DEST/$name/series.csv" || true
}

# Arm 1: compressed piecewise (the reference's 40k/60k/80k recipe scaled
# to 6k steps, resnet_cifar_train.py:302-311).
run_arm piecewise "optim.schedule=cifar_piecewise" \
  "optim.boundaries=(3000,4500,5500)" \
  "optim.values=(0.1,0.01,0.001,0.0001)"

# Arm 2: constant LR ablation — same budget, no decay.
run_arm constant "optim.schedule=constant" "optim.base_lr=0.1"

python - "$DEST" <<'EOF'
import json, os, sys
dest = sys.argv[1]
summary = {}
for arm in ("piecewise", "constant"):
    best = os.path.join(dest, arm, "best_precision.json")
    if os.path.exists(best):
        summary[arm] = json.load(open(best))
json.dump(summary, open(os.path.join(dest, "summary.json"), "w"), indent=2)
print("[convergence] summary:", json.dumps(summary))
EOF
