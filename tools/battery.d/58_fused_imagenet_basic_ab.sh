#!/usr/bin/env bash
# ImageNet rn18 fused basic-block model A/B (VERDICT r4 item 8): the
# rn18/34 stages now carry VMEM-derived tile plans
# (ops/fused_block.py::auto_batch_tile), so a stage-05 win is no longer
# CIFAR-only — measure model.fused_blocks on/off through the rn18
# ImageNet train step. GATED on stage 05 exactly like 55/57: a measured
# basic-block loss stands this down; a missing/torn gate retries.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

GATE="${FUSED_AB_GATE:-docs/runs/fused_block_ab_r${RND}.json}"
if [ ! -f "$GATE" ]; then
  echo "[fused_imagenet_basic_ab] gate artifact $GATE missing (stage 05 not run?) — will retry next window"
  exit 1
fi
python tools/ab_gate.py "$GATE"
rc=$?
if [ $rc -eq 1 ]; then
  echo "[fused_imagenet_basic_ab] stage 05 measured a loss — skipping (negative result stands)"
  exit 0
elif [ $rc -eq 2 ]; then
  echo "[fused_imagenet_basic_ab] gate evaluation failed — stage will retry next window"
  exit 1
fi

timeout -k 30 1800 python tools/fused_model_ab.py --preset imagenet \
  --resnet-size 18 \
  --out "docs/runs/fused_imagenet_basic_ab_r${RND}.json" | tail -4
