#!/usr/bin/env bash
# Fused-block hypothesis test on the live chip (docs/PERF.md "CIFAR is
# overhead-bound"): one Pallas kernel per v2 basic block vs XLA's several
# fused loops for the identical math, at the CIFAR ResNet's three stage
# shapes. Decides whether the round-4 training-path fused block (batch
# stats + custom VJP) is worth building.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
cd "$REPO"

# 8 arms x 3 shapes = 24 scan-program compiles at ~30-40 s each on a
# first-cache TPU run — 900 s would cut the decisive experiment short.
timeout -k 30 1800 python tools/fused_block_ab.py \
  --out docs/runs/fused_block_ab_r4.json | tail -8
