#!/usr/bin/env bash
# Fused-block hypothesis test on the live chip (docs/PERF.md "CIFAR is
# overhead-bound"): one Pallas kernel per v2 basic block vs XLA's several
# fused loops for the identical math, at the CIFAR ResNet's three stage
# shapes. Decides whether the round-4 training-path fused block (batch
# stats + custom VJP) is worth building.
# NO -e: the compile-smoke prelude's failure handling below must run
# after a failing command (review finding r5 — with -e a real Mosaic
# failure aborted the script before src=$? and the stage retried
# forever instead of archiving the infeasibility). Matches stage 55.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

# Compile-smoke prelude (VERDICT r4 item 3): both families are oracle-
# tested in interpret mode only, so this would otherwise be the kernels'
# first-ever Mosaic compile. A tiny non-interpret compile+run fails in
# ~1 min instead of burning the 1800 s A/B budget on a lowering error.
# SMOKE/AB_OUT overridable + COMPILE_SMOKE_FORCE=fail|timeout: the skip
# logic is CPU-testable (tests/test_compile_smoke.py) without touching
# live artifacts or running a real compile.
SMOKE="${COMPILE_SMOKE_OUT:-docs/runs/compile_smoke_block_r${RND}.json}"
AB_OUT="${FUSED_BLOCK_AB_OUT:-docs/runs/fused_block_ab_r${RND}.json}"
case "${COMPILE_SMOKE_FORCE:-}" in
  fail)
    printf '{"compile_ok": false, "error": "forced by test", "by_shape": {}}' > "$SMOKE"
    src=1 ;;
  timeout)
    src=124 ;;
  *)
    timeout -k 15 300 python tools/pallas_compile_smoke.py \
      --family block --out "$SMOKE"
    src=$? ;;
esac
if [ $src -eq 124 ] || [ $src -eq 137 ]; then
  echo "[fused_block_ab] compile smoke timed out (tunnel flake?) — will retry next window"
  exit 1
elif [ $src -ne 0 ]; then
  # Real lowering/accuracy failure: archive it AS the A/B artifact so the
  # gates (tools/ab_gate.py) read a measured infeasibility, and yield the
  # rest of the window to the headline bench (stage 10).
  cp "$SMOKE" "$AB_OUT"
  echo "[fused_block_ab] non-interpret compile FAILED — A/B skipped, error archived"
  exit 0
fi
echo "[fused_block_ab] compile smoke OK — running the A/B"

# 8 arms x 3 shapes = 24 scan-program compiles at ~30-40 s each on a
# first-cache TPU run — 900 s would cut the decisive experiment short.
timeout -k 30 1800 python tools/fused_block_ab.py \
  --out "$AB_OUT" | tail -8
