#!/usr/bin/env bash
# Single-real-chip shard_map smoke of the fused-Pallas dispatch (VERDICT
# r4 item 5): proves the Mosaic-compiled kernels work inside shard_map —
# the multi-chip story for model.fused_blocks — which the virtual-mesh
# tests cannot (interpret-mode kernels lower to plain XLA ops there).
# GATED like stage 55: only worth a window slice if stage 05 proved the
# kernels compile and win; a stage-05 loss stands this down too.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

GATE="${FUSED_AB_GATE:-docs/runs/fused_block_ab_r${RND}.json}"
if [ ! -f "$GATE" ]; then
  echo "[fused_shardmap_smoke] gate artifact $GATE missing (stage 05 not run?) — will retry next window"
  exit 1
fi
python tools/ab_gate.py "$GATE"
rc=$?
if [ $rc -eq 1 ]; then
  echo "[fused_shardmap_smoke] stage 05 measured a loss — skipping (fused path stands down)"
  exit 0
elif [ $rc -eq 2 ]; then
  echo "[fused_shardmap_smoke] gate evaluation failed — stage will retry next window"
  exit 1
fi

timeout -k 15 600 python tools/fused_shardmap_smoke.py \
  --out "docs/runs/fused_shardmap_smoke_r${RND}.json" | tail -3
