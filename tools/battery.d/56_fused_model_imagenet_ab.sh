#!/usr/bin/env bash
# ImageNet end-to-end fused-bottleneck model A/B: model.fused_blocks
# on/off through the real ImageNet train step (FusedBottleneckBlock
# dispatch) — GATED on stage 55's kernel-level A/B showing a winning
# direction. Same error discipline as stage 55: a torn gate artifact
# fails the stage (retry), a genuine loss skips it (done).
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

# Gate paths overridable for tests (see 55_fused_bottleneck_ab.sh).
GATE="${FUSED_BOTTLENECK_AB_GATE:-docs/runs/fused_bottleneck_ab_r${RND}.json}"
if [ ! -f "$GATE" ]; then
  # Missing ≠ loss: stage 55 may be unrun (crashed, or still gated on 05)
  # and retrying — keep this stage armed rather than marking it done. The
  # one legitimate skip-forever case is "stage 05 measured a loss, so 55
  # intentionally never wrote its artifact"; detect that directly from
  # stage 05's artifact.
  python tools/ab_gate.py "${FUSED_AB_GATE:-docs/runs/fused_block_ab_r${RND}.json}"
  if [ $? -eq 1 ]; then   # 1 = measured loss at stage 05 (shared rule)
    echo "[fused_model_imagenet_ab] stage 05 measured a loss; stage 55 intentionally skipped — skipping too (negative result stands)"
    exit 0
  fi
  echo "[fused_model_imagenet_ab] gate artifact $GATE missing (stage 55 unrun) — will retry next window"
  exit 1
fi
# Shared rule (tools/ab_gate.py): 0=win, 1=measured loss, 2=torn artifact.
python tools/ab_gate.py "$GATE"
rc=$?
if [ $rc -eq 1 ]; then
  echo "[fused_model_imagenet_ab] bottleneck kernel A/B shows no winning direction — skipping (negative result stands)"
  exit 0
elif [ $rc -eq 2 ]; then
  echo "[fused_model_imagenet_ab] gate evaluation failed — stage will retry next window"
  exit 1
fi

timeout -k 30 1800 python tools/fused_model_ab.py --preset imagenet \
  --out docs/runs/fused_model_imagenet_ab_r${RND}.json | tail -4
