#!/usr/bin/env bash
# ImageNet end-to-end fused-bottleneck model A/B: model.fused_blocks
# on/off through the real ImageNet train step (FusedBottleneckBlock
# dispatch) — GATED on stage 55's kernel-level A/B showing a winning
# direction. Same error discipline as stage 55: a torn gate artifact
# fails the stage (retry), a genuine loss skips it (done).
set -uo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
cd "$REPO"

GATE="docs/runs/fused_bottleneck_ab_r4.json"
if [ ! -f "$GATE" ]; then
  echo "[fused_model_imagenet_ab] gate artifact $GATE missing (stage 55 skipped or unrun) — skipping"
  exit 0
fi
python - "$GATE" <<'EOF'
import json, sys
try:
    r = json.load(open(sys.argv[1]))
    wins = [d.get("speedup", 0) > 1.0
            for shape in r.get("by_shape", {}).values()
            for name, d in shape.items() if isinstance(d, dict)]
except Exception as e:
    print(f"[fused_model_imagenet_ab] gate artifact unreadable: {e}")
    sys.exit(2)
if not wins:
    print("[fused_model_imagenet_ab] gate artifact has no measured directions")
    sys.exit(2)
sys.exit(0 if any(wins) else 1)
EOF
rc=$?
if [ $rc -eq 1 ]; then
  echo "[fused_model_imagenet_ab] bottleneck kernel A/B shows no winning direction — skipping (negative result stands)"
  exit 0
elif [ $rc -eq 2 ]; then
  echo "[fused_model_imagenet_ab] gate evaluation failed — stage will retry next window"
  exit 1
fi

timeout -k 30 1800 python tools/fused_model_ab.py --preset imagenet \
  --out docs/runs/fused_model_imagenet_ab_r4.json | tail -4
