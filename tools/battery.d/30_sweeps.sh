#!/usr/bin/env bash
# Default-tuning sweeps on the live TPU (VERDICT r2 item 7):
# steps_per_call on the resident path, transfer_stage on the streaming
# path, and resident-vs-streaming at the tuned points — the measurements
# behind config.py's data.transfer_stage / train.steps_per_call /
# data.device_resident defaults.
set -eu
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
RUNS="$REPO/docs/runs"
cd "$REPO"

timeout -k 30 1500 python - <<'EOF'
import json, sys, time
sys.path.insert(0, ".")
import bench
from tpu_resnet.parallel import create_mesh

mesh = create_mesh(None)
out = {}

# steps_per_call sweep, resident path (one shared compile cache)
plans = [(5, 2, 10), (10, 2, 10), (25, 2, 6), (50, 2, 5)]
by_k = bench._measure_cifar(mesh, plans)
out["resident_by_steps_per_call"] = {k: round(v, 2)
                                     for k, v in by_k.items()}
print("[sweeps] resident by k:", out["resident_by_steps_per_call"],
      flush=True)

# transfer_stage sweep, streaming path
stages = {}
for stage in (4, 8, 16):
    sps, bd = bench._measure_cifar_streaming(mesh, warmup_super=2,
                                             measure_super=10, stage=stage)
    stages[stage] = round(sps, 2)
    print(f"[sweeps] streaming stage={stage}: {sps:.2f} st/s "
          f"(data wait {bd['data_wait_frac']:.0%})", flush=True)
out["streaming_by_transfer_stage"] = stages

best_resident = max(out["resident_by_steps_per_call"].values())
best_streaming = max(stages.values())
out["resident_vs_streaming"] = {
    "resident_best": best_resident, "streaming_best": best_streaming,
    "resident_wins": best_resident >= best_streaming}
# quoted heredoc: read the round tag in-process, not via shell expansion
rnd = open("tools/BATTERY_ROUND").read().strip()
json.dump(out, open(f"docs/runs/sweeps_r{rnd}.json", "w"), indent=2)
print("[sweeps]", json.dumps(out))
EOF
