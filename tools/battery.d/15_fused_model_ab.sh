#!/usr/bin/env bash
# Integrated fused-block A/B on the live chip: model.fused_blocks on/off
# through the real headline path (after stage 05's kernel-level A/B and
# the stage-10 bench — a fused-path failure here must not cost the
# window's decisive artifacts). Two full-model compiles (~60-120 s each
# first-cache) plus measurement.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

timeout -k 30 1800 python tools/fused_model_ab.py \
  --out docs/runs/fused_model_ab_r${RND}.json | tail -4
