#!/usr/bin/env bash
# MFU analysis on the live TPU (VERDICT r2 item 4): HLO inventory, cost
# analysis, measured step rate and a profiler trace for the ImageNet
# train step at b128 and b256; committed artifacts are the JSON summaries
# and a gzipped compiled-HLO excerpt (the trace stays in the watch dir).
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
OUT="${1:-$REPO/docs/runs/watch_r${RND}}"
RUNS="$REPO/docs/runs"
cd "$REPO"

timeout -k 30 900 python tools/mfu_probe.py --batch 128 \
  --out "$RUNS/mfu_b128_r${RND}.json" --hlo-gz "$RUNS/hlo_imagenet_b128_r${RND}.txt.gz" \
  --trace-dir "$OUT/mfu_trace_b128" | tail -25

timeout -k 30 900 python tools/mfu_probe.py --batch 256 \
  --out "$RUNS/mfu_b256_r${RND}.json" | tail -20

# b512 needs block remat (activations past the 16 GB HBM ceiling);
# failure here must not sink the stage — record and move on.
timeout -k 30 900 python tools/mfu_probe.py --batch 512 --remat \
  --out "$RUNS/mfu_b512_remat_r${RND}.json" | tail -20 \
  || echo "[mfu] b512+remat failed (recorded nothing) — not fatal"
