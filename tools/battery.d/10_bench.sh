#!/usr/bin/env bash
# Headline bench artifact on the live chip — the full bench.py run whose
# JSON the driver compares against BASELINE.json. Runs second (after the
# fused-block A/B) per the r4 priority order. The OUTER watcher owns
# polling: short window, no CPU fallback — if the tunnel died between the
# watcher's probe and here, return to the poll loop instead of nesting
# bench.py's own 1h watch inside it.
set -u -o pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
OUT="${1:-$REPO/docs/runs/watch_r4}"
RUNS="$REPO/docs/runs"
cd "$REPO"

BENCH_PROBE_TIMEOUT=60 BENCH_TPU_ATTEMPTS=2 \
BENCH_WATCH_WINDOW=180 BENCH_CPU_FALLBACK=0 \
  python bench.py >"$OUT/bench.json" 2>"$OUT/bench.stderr"
rc=$?
if [ $rc -eq 0 ] && python - "$OUT/bench.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
ok = r.get("backend") == "tpu" and not r.get("partial")
sys.exit(0 if ok else 1)
EOF
then
  cp "$OUT/bench.json" "$RUNS/bench_r4_tpu_v5e.json"
  cp "$OUT/bench.stderr" "$RUNS/bench_r4_tpu_v5e.log"
  echo "[battery] bench complete -> docs/runs/bench_r4_tpu_v5e.json"
else
  echo "[battery] bench rc=$rc or partial — will retry next window"
  exit 1
fi
