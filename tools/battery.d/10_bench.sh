#!/usr/bin/env bash
# Headline bench artifact on the live chip — the full bench.py run whose
# JSON the driver compares against BASELINE.json. Runs second (after the
# fused-block A/B) per the r4 priority order. The OUTER watcher owns
# polling: BENCH_WATCH_WINDOW is bench.py's TOTAL budget (r5 semantics) —
# enough for one probe plus the full measurement child — and the CPU
# fallback stays off: if the tunnel died between the watcher's probe and
# here, return to the poll loop instead of burning the core.
#
# bench.py may print more than one line (a provisional line precedes the
# final one when a probe fails mid-stage), so validation parses the LAST
# line, exactly like the driver does.
set -u -o pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
OUT="${1:-$REPO/docs/runs/watch_r${RND}}"
RUNS="$REPO/docs/runs"
cd "$REPO"

BENCH_PROBE_TIMEOUT=60 BENCH_TPU_ATTEMPTS=2 \
BENCH_WATCH_WINDOW=2700 BENCH_CPU_FALLBACK=0 BENCH_MAX_PROBE_FAILS=3 \
  python bench.py >"$OUT/bench.json" 2>"$OUT/bench.stderr"
rc=$?
if [ $rc -eq 0 ] && python - "$OUT/bench.json" <<'EOF'
import json, sys
last = [l for l in open(sys.argv[1]) if l.strip()][-1]
r = json.loads(last)
ok = r.get("backend") == "tpu" and not r.get("partial")
open(sys.argv[1], "w").write(last)   # keep the artifact single-line JSON
sys.exit(0 if ok else 1)
EOF
then
  cp "$OUT/bench.json" "$RUNS/bench_r${RND}_tpu_v5e.json"
  cp "$OUT/bench.stderr" "$RUNS/bench_r${RND}_tpu_v5e.log"
  echo "[battery] bench complete -> docs/runs/bench_r${RND}_tpu_v5e.json"
else
  echo "[battery] bench rc=$rc or non-tpu/partial — will retry next window"
  exit 1
fi
