#!/usr/bin/env bash
# Resident-vs-streaming step-time isolation on the live chip (r3
# postmortem of the r2 "streaming 584 st/s" claim): times the same chunk
# program against (a) a reused device-resident superbatch, (b) the
# resident epoch buffer, (c) a device-to-device restaged block — all
# transfer-free in the timed loop, fetch-synced timing.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

timeout -k 30 900 python tools/streaming_gap_probe.py \
  --out docs/runs/streaming_gap_r${RND}.json | tail -5
