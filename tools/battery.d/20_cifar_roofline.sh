#!/usr/bin/env bash
# CIFAR step cost analysis on the live chip: is the 4.9 ms/step headline
# (203 st/s, docs/PERF.md) HBM-bandwidth-bound like the ImageNet step,
# or small-kernel/latency-bound (the 16/32/64-filter convs leave the
# 128x128 MXU mostly idle)? The measured rate authority stays bench.py's
# fused chunks — this captures the compiled cost FLOPs/bytes and HLO
# inventory behind the number.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
cd "$REPO"

OUT="${1:-$REPO/docs/runs/watch_r${RND}}"
timeout -k 30 900 python tools/mfu_probe.py --preset cifar10 --batch 128 \
  --out docs/runs/cifar_cost_r${RND}.json \
  --hlo-gz docs/runs/hlo_cifar_b128_r${RND}.txt.gz \
  --trace-dir "$OUT/cifar_trace_b128" | tail -20
