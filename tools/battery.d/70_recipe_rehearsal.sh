#!/usr/bin/env bash
# Recipe-scale schedule rehearsal on the live TPU (VERDICT r3 item 6):
# the freq100 synthetic oracle stretched to the REAL CIFAR recipe shape —
# piecewise LR with boundaries at 40k/60k/80k steps exactly per
# resnet_cifar_train.py:302-311, checkpoint every 1000 steps, eval sidecar
# polling live — so the exact production cadence the 93.6% reproduction
# would use is exercised end to end. r3 only ever ran the compressed
# 6k-step version (boundaries 3000/4500/5500); at the measured ~216 st/s
# the full 90k-step run is ~7 min of chip compute plus ckpt/eval overhead.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
OUT="${1:-$REPO/docs/runs/watch_r${RND}}"
DEST="$REPO/docs/runs/recipe_rehearsal_r${RND}"
mkdir -p "$DEST"
cd "$REPO"

RUN=/tmp/recipe_rehearsal
# The trainer auto-resumes from the latest checkpoint in train_dir
# (train/loop.py) — if a window closed mid-run, keep the partial run so the
# next window continues from the last 1000-step checkpoint instead of
# restarting a 90k-step stage from zero. Only wipe a dir with no checkpoint.
if [ -d "$RUN" ] && find "$RUN" -maxdepth 1 -type d -name '[0-9]*' | grep -q .; then
  echo "[recipe_rehearsal] resuming from existing checkpoints in $RUN"
else
  rm -rf "$RUN"
fi
timeout -k 30 3600 python -m tpu_resnet train_and_eval --preset smoke \
  data.synthetic_learnable=true data.synthetic_task=freq100 \
  data.synthetic_classes=100 data.synthetic_label_noise=0.1 \
  data.synthetic_train_examples=20480 data.synthetic_eval_examples=2048 \
  model.resnet_size=20 model.compute_dtype=bfloat16 \
  train.global_batch_size=128 train.eval_batch_size=128 \
  train.train_steps=90000 train.checkpoint_every=1000 train.log_every=500 \
  train.image_summary_every=0 \
  optim.schedule=cifar_piecewise "optim.boundaries=(40000,60000,80000)" \
  "optim.values=(0.1,0.01,0.001,0.0001)" \
  train.train_dir="$RUN" 2>&1 | tail -8

cp "$RUN/metrics.jsonl" "$DEST/train_metrics.jsonl"
cp "$RUN/eval/metrics.jsonl" "$DEST/eval_metrics.jsonl" 2>/dev/null || true
cp "$RUN/eval/best_precision.json" "$DEST/" 2>/dev/null || true
python -m tpu_resnet plot --dir "$RUN" \
  --out "$DEST/curves.png" --csv "$DEST/series.csv" || true

# Decay-boundary evidence: the loss/precision series must show jumps at
# the recipe steps, not just end-state accuracy. Extraction shared with
# the CPU understudy (tools/rehearsal_summary.py) — the understudy proved
# this exact code path before chip time was spent on it.
python tools/rehearsal_summary.py "$DEST" 40000 60000 80000 1000 \
  --what "freq100 oracle at the real 40k/60k/80k recipe cadence (resnet_cifar_train.py:302-311), ckpt every 1000, live eval sidecar"
