#!/usr/bin/env bash
# Streaming ImageNet train on the live TPU: the END-TO-END input-edge
# measurement (VERDICT r2 item 2b) — TFRecord read → JPEG decode → VGG
# preprocess → staged superbatch transfer → fused train step — over
# synthetic photo-like shards, reported as sustained st/s next to the
# synthetic-resident headline. Expected host-bound on this 1-core box
# (~510 img/s/core decode vs ~3000 img/s consumed); the honest number +
# the measured per-core decode rate IS the deliverable (host-count
# budget measured in r3: docs/runs/input_edge_r3.json).
set -euo pipefail
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
RND="$(cat "$REPO/tools/BATTERY_ROUND")"
OUT="${1:-$REPO/docs/runs/watch_r${RND}}"
SHARDS=/tmp/imagenet_synth_shards
RUN=/tmp/inet_stream_run_$$
cd "$REPO"

if [ ! -f "$SHARDS/.done" ]; then
  echo "[imagenet_stream] generating synthetic shards"
  mkdir -p "$SHARDS"
  python - <<'EOF'
import sys
sys.path.insert(0, "tools")
from input_edge import make_shards
make_shards("/tmp/imagenet_synth_shards", n_shards=8, per_shard=96)
make_shards("/tmp/imagenet_synth_shards", n_shards=2, per_shard=64,
            train=False, seed=7)
open("/tmp/imagenet_synth_shards/.done", "w").close()
EOF
fi

echo "[imagenet_stream] streaming train run (40 steps b128)"
# global_batch_size override: the imagenet preset defaults to the pod-scale
# 1024, which OOMs a single chip's HBM — b128 is the headline config.
timeout -k 30 1200 python -m tpu_resnet train --preset imagenet \
  data.data_dir="$SHARDS" \
  train.train_dir="$RUN" \
  train.global_batch_size=128 train.eval_batch_size=128 \
  train.train_steps=40 train.log_every=10 train.checkpoint_every=40 \
  train.image_summary_every=0 2>&1 | tail -20

python - "$RUN" "$REPO/docs/runs/imagenet_stream_r${RND}.json" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1] + "/metrics.jsonl")]
rates = [r["steps_per_sec"] for r in recs if "steps_per_sec" in r]
out = {
    "what": "streaming ImageNet ResNet-50 b128: host decode -> staged "
            "superbatches -> fused step (synthetic photo shards)",
    "steps_per_sec_by_log_point": [round(r, 3) for r in rates],
    "sustained_steps_per_sec": round(rates[-1], 3) if rates else None,
    "images_per_sec": round(rates[-1] * 128, 1) if rates else None,
}
json.dump(out, open(sys.argv[2], "w"), indent=2)
print(json.dumps(out))
EOF
rm -rf "$RUN"
