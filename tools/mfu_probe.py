"""Where-the-time-goes analysis for the ImageNet train step (VERDICT r2
item 4): compiled-HLO inventory + XLA cost analysis + optional profiler
trace, on the ambient backend.

    python tools/mfu_probe.py [--batch 128] [--trace-dir D] [--out JSON]
                              [--hlo-gz PATH] [--steps 12] [--no-s2d]

Reports per-category HLO op counts (convolution / fusion / transpose /
copy / all-reduce), the cost-analysis FLOPs+bytes, measured step time,
and achieved MFU vs the chip peak — the evidence behind the MFU number in
BENCH_r03 (the reference's analog was tfprof's FLOP dump,
reference resnet_single.py:58-66).
"""

import argparse
import gzip
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="imagenet",
                    choices=["imagenet", "cifar10"],
                    help="cifar10 analyzes the CIFAR-shaped step (32x32, "
                         "synthetic split, on-device augmentation included "
                         "like the real train step); note its single-step "
                         "dispatch rate is latency-skewed over a tunnel — "
                         "bench.py's fused chunks are the rate authority, "
                         "the cost analysis is what this adds")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--resnet-size", type=int, default=50)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--no-s2d", action="store_true")
    ap.add_argument("--remat", action="store_true",
                    help="block rematerialization (for batches past the "
                         "HBM ceiling, e.g. 512)")
    ap.add_argument("--trace-dir", default="")
    ap.add_argument("--hlo-gz", default="")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    import jax
    import numpy as np

    import bench
    from tpu_resnet import parallel
    from tpu_resnet.train.step import make_train_step, shard_step

    is_cifar = args.preset == "cifar10"
    if is_cifar and (args.no_s2d or args.image != 224):
        # The CIFAR generator has a 3x3/1 stem (no s2d to ablate) and a
        # fixed 32x32 shape — fail loudly rather than record metadata for
        # a configuration that was never compiled (bench.py's
        # conflicting-override convention).
        raise SystemExit("--no-s2d/--image do not apply to --preset "
                         "cifar10 (3x3 stem, fixed 32x32)")
    image = 32 if is_cifar else args.image
    classes = 10 if is_cifar else 1000

    mesh = parallel.create_mesh(None)
    cfg, model, sched, state, rng = bench._build_train_setup(
        mesh, args.preset, resnet_size=args.resnet_size, batch=args.batch,
        dtype="bfloat16", image=image, synthetic=is_cifar)
    if args.no_s2d or args.remat:
        from tpu_resnet.models import build_model
        cfg.model.stem_space_to_depth = not args.no_s2d
        cfg.model.remat = args.remat
        model = build_model(cfg)

    bs = parallel.batch_sharding(mesh)
    if is_cifar:
        from tpu_resnet.data.augment import get_augment_fns
        augment_fn, _ = get_augment_fns("cifar10")
        images = jax.device_put(
            np.random.RandomState(0).randint(
                0, 256, (args.batch, image, image, 3)).astype(np.uint8), bs)
    else:
        augment_fn = None
        images = jax.device_put(
            np.random.RandomState(0).uniform(
                -114.0, 141.0,
                (args.batch, image, image, 3)).astype(np.float32), bs)
    labels = jax.device_put(
        np.random.RandomState(1).randint(0, classes, args.batch)
        .astype(np.int32), bs)

    step_fn = shard_step(
        make_train_step(model, cfg.optim, sched, classes, augment_fn,
                        base_rng=rng, mesh=mesh), mesh)
    # donate_state=True (the default, what train/loop.py runs): XLA may
    # update params in place instead of allocating a fresh state tree —
    # the measured step is the production configuration.
    t0 = time.perf_counter()
    compiled = step_fn.lower(state, images, labels).compile()
    compile_secs = time.perf_counter() - t0

    hlo = compiled.as_text()
    ops = {}
    for m in re.finditer(r"= \S+ ([a-z][a-z0-9\-]*)\(", hlo):
        ops[m.group(1)] = ops.get(m.group(1), 0) + 1
    interesting = {k: ops.get(k, 0) for k in
                   ("convolution", "fusion", "transpose", "copy",
                    "all-reduce", "custom-call", "reduce", "scatter")}
    # async collective form some backends emit
    interesting["all-reduce"] += ops.get("all-reduce-start", 0)

    from tpu_resnet.obs.mfu import program_flops

    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    cost = cost or {}

    # measure
    for _ in range(3):
        state, m = compiled(state, images, labels)
    bench._fetch_sync(m["loss"])
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = compiled(state, images, labels)
    bench._fetch_sync(m["loss"])
    sps = args.steps / (time.perf_counter() - t0)

    kind = jax.devices()[0].device_kind
    # Shared tables/extraction: tpu_resnet/obs/mfu.py is the one home of
    # the peak-FLOPs table and the cost-analysis parsing; the probe's MFU
    # is computed exactly like the live gauge's.
    peak = bench._peak_flops(kind)
    flops = program_flops(cost) or 0.0
    out = {
        "backend": jax.default_backend(), "device_kind": kind,
        "preset": args.preset, "image": image,
        "batch": args.batch,
        # s2d only exists on the ImageNet 7x7/2 stem; None = not applicable
        "stem_space_to_depth": None if is_cifar else not args.no_s2d,
        "remat": args.remat,
        "compile_secs": round(compile_secs, 1),
        "steps_per_sec": round(sps, 3),
        "images_per_sec": round(sps * args.batch, 1),
        "hlo_op_counts": interesting,
        "hlo_total_instructions": sum(ops.values()),
        "cost_flops_per_step_per_device": flops,
        "cost_bytes_accessed": float(cost.get("bytes accessed", 0) or 0),
        "mfu": round(flops * sps / peak, 4) if peak and flops else None,
        "peak_flops_assumed": peak,
    }

    if args.trace_dir:
        os.makedirs(args.trace_dir, exist_ok=True)
        with jax.profiler.trace(args.trace_dir):
            for _ in range(5):
                state, m = compiled(state, images, labels)
            bench._fetch_sync(m["loss"])
        out["trace_dir"] = args.trace_dir

    if args.hlo_gz:
        with gzip.open(args.hlo_gz, "wt") as f:
            f.write(hlo)
        out["hlo_gz"] = args.hlo_gz

    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
