#!/usr/bin/env python3
"""Perf-regression tracker — verdicts over the bench RESULT_JSON
trajectory.

The repo accumulates one bench artifact per round (``BENCH_r0N.json``
at the root, written by the driver; ``docs/runs/bench_r*_tpu_v5e.json``
archived by the battery after validating a live-TPU run). Whether a
round's number is a win, noise, or a regression was judged by eyeball.
This tool makes the judgment mechanical and consumable by ``doctor
--perfwatch``:

- parse every artifact (the ``parsed`` field when the driver captured
  one, else salvage the last intact JSON line from the recorded stdout
  ``tail`` — the BENCH_r04 failure mode, rc=124 with parsed=null);
- extract the tracked metrics (headline CIFAR steps/sec, ImageNet
  steps/sec and MFU) as (round, backend, value) samples;
- cohort by backend — a CPU-fallback round must never be compared
  against chip numbers (BENCH_r02/r03 recorded 0.03/0.01 st/s CPU
  fallbacks while fetch-verified TPU numbers sat in docs/runs/);
- compare the newest sample of the newest-sampled cohort against the
  median of its predecessors with a configurable noise band.

Verdicts per metric: ``regress`` (below band), ``improve`` (above),
``flat`` (inside), ``insufficient_data`` (< 2 comparable samples).
Exit code: 1 if ANY tracked metric regresses, else 0.

    python tools/perfwatch.py [--root .] [--noise 0.08]
        [--add runs/new_bench.json ...] [--json verdict.json]

Stdlib-only and jax-free: runs anywhere the checkout does.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import Dict, List, Optional

HEADLINE_METRIC = "cifar10_resnet50_train_steps_per_sec_b128"

# (name, extractor) — every tracked metric is higher-is-better.
def _headline(rec: dict) -> Optional[float]:
    if rec.get("metric") == HEADLINE_METRIC:
        return rec.get("value")
    return None


def _imagenet_sps(rec: dict) -> Optional[float]:
    return (rec.get("imagenet") or {}).get("value")


def _imagenet_mfu(rec: dict) -> Optional[float]:
    return (rec.get("imagenet") or {}).get("mfu")


def _imagenet_hbm_peak(rec: dict) -> Optional[float]:
    return (rec.get("imagenet") or {}).get("hbm_bytes_peak")


METRICS = (
    ("cifar_steps_per_sec", _headline),
    ("imagenet_steps_per_sec", _imagenet_sps),
    ("imagenet_mfu", _imagenet_mfu),
    ("imagenet_hbm_peak_bytes", _imagenet_hbm_peak),
)

# Memory metrics invert the verdict: growth past the band is the
# regression (a knob that "wins" MFU by blowing the HBM budget must not
# pass silently). Bench records carry hbm_bytes_peak next to mfu
# (obs/memory.py device stats), sweep points per knob. Time-to-ready is
# the cold-start twin (doctor --coldstart-probe feeds cold/warm serve
# restart points): a restart getting SLOWER to ready is the regression.
LOWER_IS_BETTER = {"imagenet_hbm_peak_bytes"}
SWEEP_MEM_PREFIX = "sweep-mem:"
SWEEP_TTR_PREFIX = "sweep-ttr:"
SWEEP_LAT_PREFIX = "sweep-lat:"
# Scenario-conductor series (tpu_resnet/scenario): point ids are
# "<scenario>:<metric>", so any declared scenario series regression-
# gates with zero glue. Direction comes from the metric's unit suffix —
# _ms/_bytes/_s name costs (lower is better), everything else a rate.
SWEEP_SCN_PREFIX = "sweep-scn:"
# Bytes-on-wire twin (lower-is-better): bench records carry
# comms_bytes_per_step from the compiled step's collective summary
# (obs/comms.py) — a knob that "wins" throughput by inflating the
# per-step collective traffic gates as regress, the same contract as
# the peak-HBM series.
SWEEP_COMM_PREFIX = "sweep-comm:"


def _lower_is_better(name: str) -> bool:
    if name.startswith(SWEEP_SCN_PREFIX):
        return name.endswith(("_ms", "_bytes", "_s"))
    return (name in LOWER_IS_BETTER
            or name.startswith((SWEEP_MEM_PREFIX, SWEEP_TTR_PREFIX,
                                SWEEP_LAT_PREFIX, SWEEP_COMM_PREFIX)))


def salvage_result(text: str) -> Optional[dict]:
    """Last intact JSON object line in a stdout tail — accepts both the
    bare ``_emit`` line and child ``RESULT_JSON:``-prefixed snapshots,
    skipping truncated lines (the BENCH_r04 capture truncated the only
    emit mid-string; earlier complete lines, when present, still win)."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("RESULT_JSON: "):
            line = line[len("RESULT_JSON: "):]
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and ("metric" in rec or "backend" in rec):
            return rec
    return None


def _record_of(payload: dict) -> Optional[dict]:
    """A driver round file ({parsed, tail, ...}) or a raw bench snapshot
    → the bench result record."""
    if "parsed" in payload or "tail" in payload:
        rec = payload.get("parsed")
        if not rec:
            rec = salvage_result(payload.get("tail") or "")
        return rec
    return payload if isinstance(payload, dict) else None


def load_samples(root: str, extra_files=()) -> List[dict]:
    """Every (round, backend, metric, value) sample from the root's
    ``BENCH_r*.json`` + archived ``docs/runs/bench_r*_tpu_v5e.json`` +
    ``extra_files``. Samples are ordered oldest→newest: archived chip
    artifacts sort by their round number alongside the driver rounds
    (they are the same round's chip truth); extra files come last (they
    are "the new run" perfwatch is asked to judge)."""
    sources = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            sources.append((int(m.group(1)), 0, path))
    for path in glob.glob(os.path.join(root, "docs", "runs",
                                       "bench_r*_tpu_v5e.json")):
        m = re.search(r"bench_r(\d+)_tpu_v5e\.json$", path)
        if m:
            # Archived chip artifacts supersede the driver capture of the
            # same round (sort later within the round).
            sources.append((int(m.group(1)), 1, path))
    sources.sort()
    order = [path for _, _, path in sources] + list(extra_files)

    samples = []
    for idx, path in enumerate(order):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            samples.append({"source": path, "error": f"{type(e).__name__}: "
                                                     f"{e}"})
            continue
        rec = _record_of(payload)
        if not rec:
            samples.append({"source": path,
                            "error": "no parseable RESULT_JSON"})
            continue
        backend = rec.get("backend") or "unknown"
        for name, extract in METRICS:
            try:
                value = extract(rec)
            except (TypeError, AttributeError):
                value = None
            if isinstance(value, (int, float)) and value > 0:
                samples.append({"source": os.path.basename(path),
                                "order": idx, "metric": name,
                                "backend": backend, "value": float(value),
                                "partial": bool(rec.get("partial"))})
    return samples


def judge(samples: List[dict], noise: float = 0.08,
          metric_names: Optional[List[str]] = None) -> dict:
    """Per-metric verdicts. For each metric the cohort is the backend of
    its NEWEST sample; reference = median of the cohort's earlier
    samples; the verdict compares latest/reference against the ±noise
    band. ``metric_names`` overrides the default tracked set (the sweep
    path passes the per-knob point names discovered in the samples)."""
    verdict: Dict[str, dict] = {}
    errors = [s for s in samples if "error" in s]
    names = (metric_names if metric_names is not None
             else [n for n, _ in METRICS])
    for name in names:
        series = [s for s in samples if s.get("metric") == name]
        if not series:
            verdict[name] = {"verdict": "insufficient_data", "samples": 0}
            continue
        latest = series[-1]
        cohort = [s for s in series if s["backend"] == latest["backend"]]
        prior = [s["value"] for s in cohort[:-1]]
        entry = {"backend": latest["backend"],
                 "latest": latest["value"],
                 "latest_source": latest["source"],
                 "samples": len(cohort)}
        if not prior:
            entry["verdict"] = "insufficient_data"
        else:
            ref = statistics.median(prior)
            ratio = latest["value"] / ref if ref else float("inf")
            entry.update(reference=round(ref, 6), ratio=round(ratio, 4),
                         noise_band=noise)
            lower = _lower_is_better(name)
            if lower:
                entry["direction"] = "lower_is_better"
            if ratio < 1.0 - noise:
                entry["verdict"] = "improve" if lower else "regress"
            elif ratio > 1.0 + noise:
                entry["verdict"] = "regress" if lower else "improve"
            else:
                entry["verdict"] = "flat"
        verdict[name] = entry
    verdicts = {v["verdict"] for v in verdict.values()}
    overall = ("regress" if "regress" in verdicts
               else "improve" if "improve" in verdicts
               else "flat" if "flat" in verdicts
               else "insufficient_data")
    return {"overall": overall, "noise": noise, "metrics": verdict,
            "unparseable_sources": [e["source"] for e in errors]}


def sweep_record_of(payload) -> Optional[dict]:
    """A sweep trajectory (tools/sweep.py ``--json`` artifact, a raw
    RESULT_JSON dict, or a driver-style {parsed|tail} wrapper) → the
    trajectory record, else None."""
    rec = _record_of(payload) if isinstance(payload, dict) else None
    if isinstance(rec, dict) and isinstance(rec.get("points"), list):
        return rec
    return None


def sweep_point_statuses(path: str) -> Dict[str, str]:
    """point id → status for one sweep trajectory file ({} when
    unreadable)."""
    try:
        with open(path) as f:
            rec = sweep_record_of(json.load(f))
    except (OSError, ValueError):
        return {}
    if rec is None:
        return {}
    return {str(p.get("id")): str(p.get("status"))
            for p in rec["points"] if p.get("id")}


def apply_sweep_statuses(verdict: dict, latest_statuses: Dict[str, str]
                         ) -> dict:
    """A point that succeeded in earlier sweep runs but FAILED in the
    newest one is the worst possible regression — value-based judging
    alone would degrade it to insufficient_data (no latest sample).
    skipped_timeout/error gate as ``regress``; ``skipped_budget`` is the
    harness's own scheduling (operator shrank the budget), reported as
    ``not_measured`` without gating."""
    for name, entry in verdict["metrics"].items():
        pid = name.split(":", 1)[1] if ":" in name else name
        status = latest_statuses.get(pid)
        if status in (None, "ok"):
            continue
        entry["latest_status"] = status
        if status == "skipped_budget":
            entry["verdict"] = "not_measured"
        else:
            entry["verdict"] = "regress"
            entry["reason"] = (f"point completed in earlier runs but "
                               f"ended '{status}' in the newest")
    verdicts = {v["verdict"] for v in verdict["metrics"].values()}
    verdict["overall"] = ("regress" if "regress" in verdicts
                          else "improve" if "improve" in verdicts
                          else "flat" if "flat" in verdicts
                          else "insufficient_data")
    return verdict


def load_sweep_samples(paths: List[str]) -> List[dict]:
    """Per-knob samples from an ordered (oldest → newest) list of sweep
    trajectory files: every completed point becomes a
    ``sweep:<point_id>`` metric sample, cohorted by the point's backend
    like the headline metrics — so a CPU-fallback sweep is never judged
    against chip numbers."""
    samples: List[dict] = []
    for idx, path in enumerate(paths):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            samples.append({"source": path,
                            "error": f"{type(e).__name__}: {e}"})
            continue
        rec = sweep_record_of(payload)
        if rec is None:
            samples.append({"source": path,
                            "error": "no sweep trajectory (missing "
                                     "'points')"})
            continue
        for point in rec["points"]:
            if point.get("status") != "ok":
                continue
            backend = (point.get("backend") or rec.get("backend")
                       or "unknown")
            value = point.get("steps_per_sec")
            if isinstance(value, (int, float)) and value > 0:
                samples.append({
                    "source": os.path.basename(path), "order": idx,
                    "metric": f"sweep:{point.get('id')}",
                    "backend": backend,
                    "value": float(value), "partial": False})
            # Peak-HBM twin of the throughput sample (lower-is-better):
            # judged with the same cohort/noise machinery, so a knob
            # whose "win" blows the memory budget gates as regress.
            mem = point.get("hbm_bytes_peak")
            if isinstance(mem, (int, float)) and mem > 0:
                samples.append({
                    "source": os.path.basename(path), "order": idx,
                    "metric": f"{SWEEP_MEM_PREFIX}{point.get('id')}",
                    "backend": backend,
                    "value": float(mem), "partial": False})
            # Time-to-ready twin (lower-is-better): the coldstart probe's
            # cold/warm serve restart points — a warm restart drifting
            # back toward cold-start times (an executable-cache
            # regression) gates as regress across probe runs.
            ttr = point.get("time_to_ready_s")
            if isinstance(ttr, (int, float)) and ttr > 0:
                samples.append({
                    "source": os.path.basename(path), "order": idx,
                    "metric": f"{SWEEP_TTR_PREFIX}{point.get('id')}",
                    "backend": backend,
                    "value": float(ttr), "partial": False})
            # Serving-latency twin (lower-is-better): fleetmon's merged
            # fleet p99 and burn-rate series from the doctor probe — a
            # latency regression across probe runs gates exactly like a
            # throughput one.
            lat = point.get("latency_ms")
            if isinstance(lat, (int, float)) and lat > 0:
                samples.append({
                    "source": os.path.basename(path), "order": idx,
                    "metric": f"{SWEEP_LAT_PREFIX}{point.get('id')}",
                    "backend": backend,
                    "value": float(lat), "partial": False})
            # Bytes-on-wire twin (lower-is-better): the compiled step's
            # per-step collective traffic (obs/comms.py summary via
            # bench) — a throughput "win" that inflates wire traffic
            # gates as regress before it ever meets a real pod.
            comm = point.get("comms_bytes_per_step")
            if isinstance(comm, (int, float)) and comm > 0:
                samples.append({
                    "source": os.path.basename(path), "order": idx,
                    "metric": f"{SWEEP_COMM_PREFIX}{point.get('id')}",
                    "backend": backend,
                    "value": float(comm), "partial": False})
            # Scenario-conductor series: the point id already carries
            # "<scenario>:<metric>"; direction is derived from the
            # metric's unit suffix in _lower_is_better.
            scn = point.get("scenario_value")
            if isinstance(scn, (int, float)) and scn > 0:
                samples.append({
                    "source": os.path.basename(path), "order": idx,
                    "metric": f"{SWEEP_SCN_PREFIX}{point.get('id')}",
                    "backend": backend,
                    "value": float(scn), "partial": False})
    return samples


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="repo root holding BENCH_r*.json (default: this checkout)")
    ap.add_argument("--noise", type=float, default=0.08,
                    help="relative noise band; a latest/reference ratio "
                         "inside 1±noise is 'flat' (default 0.08 — run-"
                         "to-run swing measured on the rehearsal box)")
    ap.add_argument("--add", action="append", default=[],
                    help="extra result file(s) to judge as the newest "
                         "run (bench emit JSON or driver round file); "
                         "repeatable")
    ap.add_argument("--sweep", action="append", default=[],
                    help="judge per-knob sweep trajectories "
                         "(tools/sweep.py artifacts) instead of the "
                         "bench trajectory; repeatable, ordered oldest "
                         "to newest — each point id is cohorted and "
                         "judged across the runs")
    ap.add_argument("--json", default="",
                    help="also write the verdict JSON to this path")
    args = ap.parse_args(argv)

    if args.sweep:
        samples = load_sweep_samples(args.sweep)
        names = sorted({s["metric"] for s in samples if "metric" in s})
        verdict = judge(samples, noise=args.noise, metric_names=names)
        verdict = apply_sweep_statuses(
            verdict, sweep_point_statuses(args.sweep[-1]))
    else:
        samples = load_samples(args.root, extra_files=args.add)
        verdict = judge(samples, noise=args.noise)

    for name, entry in verdict["metrics"].items():
        line = f"[perfwatch] {name:24s} {entry['verdict']:18s}"
        if "ratio" in entry:
            line += (f" latest={entry['latest']:g} "
                     f"ref={entry['reference']:g} "
                     f"ratio={entry['ratio']:g} "
                     f"({entry['backend']}, n={entry['samples']})")
        elif "latest" in entry:
            line += (f" latest={entry['latest']:g} "
                     f"({entry['backend']}, n={entry['samples']})")
        print(line)
    print(f"[perfwatch] overall: {verdict['overall']} "
          f"(noise band ±{args.noise:.0%})")
    print("PERFWATCH_JSON: " + json.dumps(verdict))
    if args.json:
        tmp = args.json + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(verdict, f, indent=1)
        os.replace(tmp, args.json)
    return 1 if verdict["overall"] == "regress" else 0


if __name__ == "__main__":
    sys.exit(main())
