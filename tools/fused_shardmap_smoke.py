"""Single-chip shard_map smoke of the fused-Pallas block dispatch
(VERDICT r4 item 5, battery stage 57): on the live TPU, run ONE training
step of the fused CIFAR model through the shard_map per-replica-BN path
with NON-INTERPRET kernels, and compare its loss against the jit path on
the identical batch.

This is the real-hardware analog of dryrun path 5: the virtual-mesh test
passes with interpret-mode kernels (which lower to ordinary XLA ops), so
it cannot prove that the Mosaic-compiled Pallas custom call works inside
shard_map. One chip is enough for that proof — the shard_map machinery,
collectives and custom-call integration are identical; only the axis
size changes.

    python tools/fused_shardmap_smoke.py --out docs/runs/x.json

Exit 0 with ``ok: true`` when the step runs, the loss is finite, and it
matches the jit arm within tolerance; exit 1 otherwise (error captured).
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TOL = 5e-2   # bf16 loss-scale tolerance between dispatch styles


def _run():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resnet.config import load_config
    from tpu_resnet import parallel
    from tpu_resnet.data.augment import get_augment_fns
    from tpu_resnet.data.cifar import synthetic_data
    from tpu_resnet.models import build_model
    from tpu_resnet.train import build_schedule, init_state
    from tpu_resnet.train.step import make_train_step, shard_step

    cfg = load_config("cifar10")
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_classes = 10
    cfg.model.fused_blocks = True
    cfg.model.sync_bn = False
    cfg.train.global_batch_size = 128

    mesh = parallel.create_mesh(None, devices=jax.devices()[:1])
    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    state = init_state(model, cfg.optim, sched, jax.random.PRNGKey(0),
                       jnp.zeros((1, 32, 32, 3)))
    state = jax.device_put(state, parallel.replicated(mesh))

    augment_fn, _ = get_augment_fns("cifar10")
    images, labels = synthetic_data(cfg.train.global_batch_size, 32, 10)
    bs = parallel.batch_sharding(mesh)
    gi = jax.device_put(images, bs)
    gl = jax.device_put(labels.astype(np.int32), bs)

    def step(grad_axis):
        return make_train_step(model, cfg.optim, sched,
                               cfg.data.num_classes, augment_fn,
                               base_rng=jax.random.PRNGKey(1),
                               grad_axis=grad_axis)

    # Arm A: shard_map per-replica-BN dispatch (the multi-chip story).
    sm_state, sm_metrics = shard_step(step("data"), mesh,
                                      per_replica_bn=True)(state, gi, gl)
    sm_loss = float(jax.device_get(sm_metrics["loss"]))

    # Arm B: plain jit on the same mesh/batch (the measured 05/15 path).
    # On ONE chip the two must agree: same batch, same moments.
    jit_state, jit_metrics = shard_step(step(None), mesh)(state, gi, gl)
    jit_loss = float(jax.device_get(jit_metrics["loss"]))

    return {
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "shardmap_loss": sm_loss,
        "jit_loss": jit_loss,
        "abs_diff": abs(sm_loss - jit_loss),
        "ok": (np.isfinite(sm_loss) and np.isfinite(jit_loss)
               and abs(sm_loss - jit_loss) < _TOL),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ns = ap.parse_args(argv)
    t0 = time.time()
    try:
        art = _run()
    except Exception:
        art = {"ok": False, "error": traceback.format_exc()[-2000:]}
    art["elapsed_s"] = round(time.time() - t0, 1)
    with open(ns.out, "w") as f:
        json.dump(art, f, indent=1)
    print(f"[fused_shardmap_smoke] "
          f"{'OK' if art['ok'] else 'FAIL'} {json.dumps(art)[:300]}")
    return 0 if art["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
