#!/usr/bin/env bash
# LR-schedule ablation on the freq100 hard task (VERDICT r2 item 6):
# compressed piecewise (the reference's 40k/60k/80k CIFAR recipe scaled
# to the step budget, reference resnet_cifar_train.py:302-311) vs
# constant LR, identical everything else. CPU-mesh scale (resnet8 b64
# 1200 steps) so it runs without a TPU window; the TPU-scale version is
# the r3 battery convergence stage (artifacts: docs/runs/convergence_freq100). The piecewise arm's config is identical
# to tools/convergence_bn_delta.sh's bn_sync arm — if that artifact
# exists it is reused rather than re-run.
#
# Command lines contain "sched_" so tools/tpu_battery.sh pauses these
# while TPU timing runs.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
DEST="$REPO/docs/runs/convergence_freq100"
mkdir -p "$DEST"
cd "$REPO"

COMMON="--preset smoke data.synthetic_learnable=true \
  data.synthetic_task=freq100 data.synthetic_classes=100 \
  data.synthetic_label_noise=0.1 data.synthetic_train_examples=8192 \
  data.synthetic_eval_examples=2048 model.resnet_size=8 \
  train.global_batch_size=64 train.train_steps=1200 \
  train.checkpoint_every=500 train.log_every=100 \
  train.eval_batch_size=64 train.image_summary_every=0"

run_arm () {
  name="$1"; shift
  out="$DEST/sched_$name"
  if [ -f "$out/best_precision.json" ]; then
    echo "[sched] $name already done"; return
  fi
  if [ "$name" = piecewise ] && [ -f "$DEST/bn_sync/best_precision.json" ]; then
    echo "[sched] piecewise == bn_sync arm (identical config); reusing"
    mkdir -p "$out"
    cp "$DEST/bn_sync/"* "$out/"
    return
  fi
  echo "[sched] arm $name start $(date -u +%T)"
  rm -rf "/tmp/sched_${name}_arm"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    nice -n 19 python -m tpu_resnet train_and_eval $COMMON "$@" \
    train.train_dir="/tmp/sched_${name}_arm" 2>&1 | tail -3
  mkdir -p "$out"
  cp "/tmp/sched_${name}_arm/metrics.jsonl" "$out/train_metrics.jsonl"
  cp "/tmp/sched_${name}_arm/eval/metrics.jsonl" "$out/eval_metrics.jsonl" \
    2>/dev/null || true
  cp "/tmp/sched_${name}_arm/eval/best_precision.json" "$out/" \
    2>/dev/null || true
  echo "[sched] arm $name done $(date -u +%T)"
}

run_arm piecewise "optim.schedule=cifar_piecewise" \
  "optim.boundaries=(600,900,1100)" "optim.values=(0.1,0.01,0.001,0.0001)"
run_arm constant "optim.schedule=constant" "optim.base_lr=0.1"

python - "$DEST" <<'EOF'
import json, os, sys
dest = sys.argv[1]
out = {}
for arm in ("piecewise", "constant"):
    p = os.path.join(dest, f"sched_{arm}", "best_precision.json")
    if os.path.exists(p):
        out[arm] = json.load(open(p))
json.dump(out, open(os.path.join(dest, "schedule_ablation.json"), "w"),
          indent=2)
print("[sched] summary:", json.dumps(out))
EOF
