"""ImageNet input-edge proof — measures whether the host pipeline can
actually feed the chip (VERDICT round-2 item 2).

The reference ran its full pipeline against real shards
(reference resnet_imagenet_train.py:161-187: TFRecord read → JPEG decode →
VGG preprocess → train). This environment has no dataset bytes and no
egress, so stage 1 synthesizes photo-like JPEG TFRecord shards in the
reference's exact shard format (train-XXXXX-of-NNNNN, Example keys
image/encoded + image/class/label, resnet_imagenet_train.py:105-140);
stage 2 runs the real ``ImageNetIterator`` (shuffle buffer, thread-pool
decode, fixed batches) over them and reports sustained images/s/host by
worker count, native vs PIL; stage 3 compares against what a chip
consumes at a given train rate — the honest "produced vs consumed" table.

    python tools/input_edge.py [--shards 8] [--per-shard 96] [--out JSON]

Single-core caveat (this box): thread scaling cannot exceed 1 core, so
worker counts here measure overhead, not scaling; the per-core rate is
the transferable number. A TPU-VM v5e host has 112 vCPU cores.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_shards(out_dir: str, n_shards: int = 8, per_shard: int = 96,
                seed: int = 0, train: bool = True) -> None:
    """Photo-like JPEGs (mixed sizes around the ImageNet mean ~470x390,
    one shared entropy recipe with the host-decode bench:
    bench._synthetic_photo_jpeg) wrapped as Inception-style Examples with
    1-based labels."""
    from bench import _synthetic_photo_jpeg
    from tpu_resnet.data import tfrecord

    rng = np.random.default_rng(seed)
    sizes = [(500, 375), (640, 480), (375, 500), (256, 341), (800, 600)]
    prefix = "train" if train else "validation"
    for s in range(n_shards):
        records = []
        for i in range(per_shard):
            size = sizes[int(rng.integers(len(sizes)))]
            jpeg = _synthetic_photo_jpeg(
                size, rng=rng,
                freqs=(rng.uniform(2, 12), rng.uniform(2, 10)))
            records.append(tfrecord.encode_example({
                "image/encoded": [jpeg],
                "image/class/label": [int(rng.integers(1, 1001))],
            }))
        tfrecord.write_records(
            os.path.join(out_dir,
                         f"{prefix}-{s:05d}-of-{n_shards:05d}"), records)


def measure_iterator(data_dir: str, batch: int, workers: int,
                     use_native: bool, n_batches: int = 6) -> float:
    """Sustained images/s of ImageNetIterator (decode + shuffle + batch)."""
    from tpu_resnet.data.imagenet import ImageNetIterator

    it = iter(ImageNetIterator(data_dir, batch, num_workers=workers,
                               shuffle_buffer=256, use_native=use_native))
    # Warm AND drain: workers pre-decode up to queue-depth+in-flight
    # batches during warmup; timing must start from an empty backlog or
    # multi-worker rates are inflated by pre-decoded work.
    for _ in range(workers + 4):
        next(it)
    n_batches = max(n_batches, 2 * workers)
    t0 = time.perf_counter()
    got = 0
    for _ in range(n_batches):
        images, labels = next(it)
        got += len(labels)
    return got / (time.perf_counter() - t0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--per-shard", type=int, default=96)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--workers", default="1,2,4")
    ap.add_argument("--chip-images-per-sec", type=float, default=2999.0,
                    help="consumption rate to compare against (default: "
                    "the measured b128 ImageNet step rate x 128, "
                    "docs/runs/bench_r2_tpu_v5e.json)")
    ap.add_argument("--host-cores", type=int, default=112,
                    help="cores on a real TPU-VM host (v5e: 112) for the "
                    "extrapolated host budget")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    out = {"batch": args.batch, "cores_here": len(os.sched_getaffinity(0))}
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        make_shards(d, args.shards, args.per_shard)
        out["shard_gen_secs"] = round(time.perf_counter() - t0, 1)
        out["n_images"] = args.shards * args.per_shard

        rates = {}
        curve = {}          # worker -> img/s, native decoder
        for native in (True, False):
            for w in [int(x) for x in args.workers.split(",")]:
                r = measure_iterator(d, args.batch, w, native)
                rates[f"{'native' if native else 'pil'}_w{w}"] = round(r, 1)
                if native:
                    curve[w] = r
                print(f"[input_edge] {'native' if native else 'pil':6s} "
                      f"workers={w}: {r:7.1f} img/s", flush=True)
        out["iterator_images_per_sec"] = rates

    # The images/sec-vs-workers CURVE, stated explicitly (VERDICT r4 item
    # 7: the cores-per-chip estimate must come from the curve, not one
    # point — and thread scaling is only OBSERVABLE when the box has at
    # least as many cores as workers; on a 1-core box the curve documents
    # the single-core ceiling and thread overhead honestly).
    cores = out["cores_here"]
    ws = sorted(curve)
    base_w = ws[0]          # efficiency baseline: smallest swept count
    out["scaling_curve_native"] = {
        str(w): {
            "images_per_sec": round(curve[w], 1),
            # parallel efficiency vs (w/base) x baseline rate; meaningful
            # only where the box could actually run w workers in parallel
            "efficiency_vs_linear": (
                round(curve[w] * base_w / (w * curve[base_w]), 3)
                if cores >= w and w > base_w else None),
        } for w in ws}
    out["scaling_observable_up_to_workers"] = min(cores, max(ws))
    effs = [v["efficiency_vs_linear"]
            for v in out["scaling_curve_native"].values()
            if v["efficiency_vs_linear"] is not None]
    out["observed_parallel_efficiency"] = min(effs) if effs else None

    out["chip_images_per_sec"] = args.chip_images_per_sec
    # The honest host budget: cores needed to keep one chip fed, derived
    # from the curve (VERDICT r4 item 7). Two regimes, no double
    # counting (review finding r5: a multi-worker rate already embodies
    # parallel inefficiency — dividing it by the efficiency again
    # inflates the budget):
    # - scaling observable (cores > 1): the measured best rate over the
    #   cores that produced it IS the per-core rate, inefficiency
    #   included; extrapolate linearly from there.
    # - 1-core box: the single-worker (baseline) rate is the per-core
    #   ceiling; the linear assumption is stated, not hidden.
    if cores > 1:
        best_w = max(curve, key=lambda w: curve[w])
        per_core = curve[best_w] / min(cores, best_w)
        basis = (f"measured {curve[best_w]:.0f} img/s at {best_w} "
                 f"workers on {cores} cores (inefficiency included); "
                 f"linear extrapolation beyond that")
    else:
        per_core = curve[base_w]
        basis = (f"single-core rate at {base_w} worker(s); linear "
                 f"scaling across cores assumed — parallel efficiency "
                 f"unmeasurable on a 1-core box")
    out["best_images_per_sec_per_core"] = round(per_core, 1)
    need = args.chip_images_per_sec / per_core
    out["cores_needed_per_chip"] = round(need, 1)
    out["cores_needed_assumes"] = basis
    out["host_cores_assumed"] = args.host_cores
    out["one_host_feeds_chips"] = round(args.host_cores / need, 2)
    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
