"""Quantify the f=512 fused-bottleneck exclusion (VERDICT r4 item 4).

ResNet-50's two 7²x2048 identity bottlenecks are the only identity
blocks without a fused-kernel plan (ops/fused_bottleneck.py:24-27: their
three weight matrices alone are ~17.8 MB fp32, above the ~16 MB core
VMEM). This tool replaces the bare assertion with numbers: an explicit
per-block HBM-traffic model of what XLA materializes for an identity
bottleneck versus what the fused kernel moves, across every rn50 stage —
so the f=512 share of the harvestable traffic is stated, not implied.

Model (bytes/image, fp32 accounting; bf16 halves everything uniformly):
the XLA arm materializes x, pre1, c1, pre2, mid, pre3, r, y — each
written once and read once by the consumer fusion, counted once here
(generous to XLA: perfect elementwise fusion into the convs, no
spills). The fused arm reads x and writes y, plus the halo re-reads
(row_tile+2)/row_tile on x. Chip refinement: battery stage 20/50 cost
analysis (`xla_cost_analysis` bytes-accessed) replaces this model with
measured numbers when a window opens; the model's structure matches the
r3 mfu artifacts' flops/bytes accounting.

    python tools/f512_traffic.py [--out docs/runs/f512_exclusion_r5.json]
"""

import argparse
import json
import sys

# rn50 stages: (spatial, f, channels=4f, identity_blocks)
# resnet_model_official.py:352-358 — blocks (3,4,6,3), first block of
# each stage is the projection/transition (never fused).
_STAGES = [(56, 64, 256, 2), (28, 128, 512, 3),
           (14, 256, 1024, 5), (7, 512, 2048, 2)]


def block_traffic(spatial, f, c4, row_tile=14):
    """(xla_bytes, fused_bytes) per image for one identity bottleneck."""
    px = spatial * spatial * 4          # fp32 bytes per channel-pixel
    big = px * c4                       # x / pre1 / r / y -shaped
    small = px * f                      # c1 / pre2 / mid / pre3 -shaped
    xla = 2 * (4 * big + 4 * small)     # each tensor written + read once
    halo = min(row_tile + 2, spatial) / min(row_tile, spatial)
    fused = big * (1 + halo)            # y write + haloed x read
    return xla, fused


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="")
    ns = ap.parse_args(argv)

    rows = {}
    tot_xla = tot_saving = 0.0
    f512_saving = 0.0
    for spatial, f, c4, n_blocks in _STAGES:
        xla, fused = block_traffic(spatial, f, c4)
        saving = (xla - fused) * n_blocks
        rows[f"f{f}_{spatial}x{spatial}"] = {
            "identity_blocks": n_blocks,
            "xla_mb_per_image_per_block": round(xla / 2**20, 3),
            "fused_mb_per_image_per_block": round(fused / 2**20, 3),
            "traffic_reduction_x": round(xla / fused, 2),
            "stage_saving_mb_per_image": round(saving / 2**20, 3),
            "fused_plan": f != 512,
        }
        tot_xla += xla * n_blocks
        tot_saving += saving
        if f == 512:
            f512_saving = saving

    out = {
        "what": ("analytic HBM-traffic model of rn50 identity "
                 "bottlenecks: XLA-materialized vs fused-kernel bytes "
                 "(VERDICT r4 item 4 — quantifying the f=512 exclusion); "
                 "chip-measured refinement comes from battery stages "
                 "20/50 cost analysis"),
        "by_stage": rows,
        "identity_block_xla_traffic_mb_per_image": round(
            tot_xla / 2**20, 2),
        "fused_eligible_saving_mb_per_image": round(
            (tot_saving - f512_saving) / 2**20, 2),
        "f512_saving_mb_per_image": round(f512_saving / 2**20, 2),
        "f512_share_of_harvestable_saving": round(
            f512_saving / tot_saving, 4),
    }
    print(json.dumps(out, indent=2))
    if ns.out:
        with open(ns.out, "w") as f:
            json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
