#!/usr/bin/env bash
# Docker bring-up — the TPU-native replacement for the reference's
# container-per-task launchers (start-resnet-cifar-train.sh: bridge net
# 10.20.30.0/24, one container per ps/worker with static IPs and
# CUDA_VISIBLE_DEVICES pinning; start-resnet-*-horovod-train.sh: sshd +
# mpirun mesh across containers; start-macvlan-2host.sh: macvlan for real
# multi-machine).
#
# All of that collapses to "one container per host running the same
# program": container 0 is the jax.distributed coordinator, the rest
# rendezvous to it. No ps/worker roles, no ssh keys, no mpirun — the
# collectives live in XLA, reached through the coordinator handshake.
#
#   ./launch/docker_cluster.sh [N] [IMAGE] [extra config overrides...]
#
# Env:
#   NET_MODE=bridge|macvlan   docker network driver (macvlan + PARENT_IF
#                             for real multi-machine, like the reference's
#                             start-macvlan-2host.sh)
#   PARENT_IF=eth0            parent interface for macvlan
#   SUBNET=10.20.30.0/24      network subnet (reference uses the same)
#   DEVICE_FLAGS="--privileged -v /dev:/dev"   accelerator passthrough
#   EVAL_SIDECAR=1            also start an eval container polling the
#                             shared train dir (the reference's tf-eval
#                             container, start-resnet-imagenet-main.sh tail)
set -euo pipefail
cd "$(dirname "$0")/.."

N="${1:-4}"; shift || true
IMAGE="${1:-tpu_resnet:latest}"; shift || true
NET="${NET:-tpu-resnet-net}"
SUBNET="${SUBNET:-10.20.30.0/24}"
NET_MODE="${NET_MODE:-bridge}"
TRAIN_DIR="${TRAIN_DIR:-/tmp/tpu_resnet/docker-run}"
COORD_IP="${SUBNET%.*/*}.100"
PORT=8476

docker network inspect "$NET" >/dev/null 2>&1 || \
  if [ "$NET_MODE" = macvlan ]; then
    docker network create -d macvlan --subnet="$SUBNET" \
      -o parent="${PARENT_IF:-eth0}" "$NET"
  else
    docker network create --subnet="$SUBNET" "$NET"
  fi

mkdir -p "$TRAIN_DIR"
cids=()
for ((i = 0; i < N; i++)); do
  ip="${SUBNET%.*/*}.$((100 + i))"
  cids+=("$(docker run -d --name "tpu-resnet-$i" --rm \
    --network "$NET" --ip "$ip" \
    -v "$PWD:/workspace" -v "$TRAIN_DIR:$TRAIN_DIR" -w /workspace \
    -e TPU_COORDINATOR_ADDRESS="$COORD_IP:$PORT" \
    -e TPU_NUM_PROCESSES="$N" \
    -e TPU_PROCESS_ID="$i" \
    ${DEVICE_FLAGS:-} \
    "$IMAGE" python -m tpu_resnet train \
      "$@" train.train_dir="$TRAIN_DIR")")
  echo "started tpu-resnet-$i @ $ip (${cids[-1]})"
done

if [ "${EVAL_SIDECAR:-0}" = 1 ]; then
  docker run -d --name tpu-resnet-eval --rm --network "$NET" \
    -v "$PWD:/workspace" -v "$TRAIN_DIR:$TRAIN_DIR" -w /workspace \
    "$IMAGE" python -m tpu_resnet eval "$@" train.train_dir="$TRAIN_DIR"
  echo "started eval sidecar"
fi

echo "follow logs: docker logs -f tpu-resnet-0"
echo "teardown:    ./launch/stop.sh docker"
