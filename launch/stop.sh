#!/usr/bin/env bash
# Teardown — the reference's stop.sh / stop-2.sh / mkl-scripts/kill.sh
# equivalent, scoped to this framework's processes (and containers with
# `stop.sh docker`) instead of `kill -9` on all python.
set -uo pipefail
if [ "${1:-}" = docker ]; then
  NET="${NET:-tpu-resnet-net}"
  ids="$(docker ps -aq --filter name='tpu-resnet-')"
  if [ -n "$ids" ]; then
    docker stop $ids
    docker wait $ids 2>/dev/null || true  # let --rm removal finish
  fi
  # endpoints can take a moment to detach even after wait
  for _ in 1 2 3 4 5; do
    docker network rm "$NET" 2>/dev/null && break
    docker network inspect "$NET" >/dev/null 2>&1 || break
    sleep 1
  done
  if docker network inspect "$NET" >/dev/null 2>&1; then
    echo "warning: network $NET still present (active endpoints?)" >&2
  fi
  echo "stopped tpu-resnet containers"
  exit 0
fi
pkill -f "python -m tpu_resnet" 2>/dev/null
pkill -f "tpu_resnet/main.py" 2>/dev/null
echo "stopped tpu_resnet processes"
