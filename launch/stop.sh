#!/usr/bin/env bash
# Teardown — the reference's stop.sh / mkl-scripts/kill.sh equivalent,
# scoped to this framework's processes instead of `kill -9` on all python.
set -uo pipefail
pkill -f "python -m tpu_resnet" 2>/dev/null
pkill -f "tpu_resnet/main.py" 2>/dev/null
echo "stopped tpu_resnet processes"
