#!/usr/bin/env bash
# TPU tunnel watcher — the builder-session companion to `bench.py`'s own
# long-window probe loop. The axon-attached chip flaps (round-2 postmortem:
# live windows of ~30 min separated by hours); this loop polls cheaply and
# fires the measurement battery the moment `jax.devices()` succeeds, so a
# live window is never wasted on human reaction time.
#
#   launch/tpu_watch.sh [outdir] [deadline_epoch]
#
# Probes in a short-timeout subprocess (a down tunnel blocks jax.devices()
# forever with ~0 CPU — never probe in-process). On success runs
# `tools/tpu_battery.sh`, which archives results under docs/runs/ and
# leaves a DONE marker; the watcher exits after one successful battery.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-$REPO/docs/runs/watch_r$(cat "$REPO/tools/BATTERY_ROUND")}"
DEADLINE="${2:-$(($(date +%s) + 11 * 3600))}"
PROBE_TIMEOUT="${TPU_WATCH_PROBE_TIMEOUT:-60}"
SLEEP="${TPU_WATCH_SLEEP:-90}"
mkdir -p "$OUT"

echo "[watch] start $(date -u +%FT%TZ) deadline=$(date -u -d @"$DEADLINE" +%FT%TZ) out=$OUT"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout -k 10 "$PROBE_TIMEOUT" python -c \
      "import jax; d=jax.devices(); print('LIVE', len(d), d[0].device_kind)" \
      >>"$OUT/probe.log" 2>&1; then
    echo "[watch] TPU LIVE at $(date -u +%FT%TZ) — running battery"
    bash "$REPO/tools/tpu_battery.sh" "$OUT" 2>&1 | tee -a "$OUT/battery.log"
    if [ -f "$OUT/DONE" ]; then
      echo "[watch] battery complete $(date -u +%FT%TZ)"
      exit 0
    fi
    echo "[watch] battery incomplete (window closed?) — resuming poll"
  fi
  sleep "$SLEEP"
done
echo "[watch] deadline reached without a complete battery"
exit 1
