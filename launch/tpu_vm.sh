#!/usr/bin/env bash
# Launch on a (multi-host) Cloud TPU VM slice: one process per host.
#
# Replaces BOTH reference bring-up stacks at once — the docker ps/worker
# scripts (start-resnet-*-train.sh: one container per ps/worker task with
# static IPs) and the mpirun/ssh Horovod mesh
# (start-resnet-*-horovod-train.sh:119-140) — because on TPU the only
# topology job left is "run the same program on every host":
# jax.distributed.initialize auto-discovers coordinator/topology from the
# TPU VM metadata, and XLA runs collectives over ICI.
#
#   ./launch/tpu_vm.sh <tpu-name> <zone> [--preset imagenet ...]
set -euo pipefail

TPU_NAME="$1"; shift
ZONE="$1"; shift

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd \$(mktemp -d) && git clone ${REPO_URL:-<this-repo>} repo \
             && cd repo && python -m tpu_resnet.native.build || true \
             && python -m tpu_resnet train $*"
