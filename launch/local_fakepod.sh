#!/usr/bin/env bash
# Single-process fake pod: N virtual CPU devices in one process — the
# smallest way to exercise the data-parallel mesh without hardware.
# Replaces the reference's localhost smoke configs
# (mkl-scripts/run_local.sh, run_dist_tf_local.sh: batch 10, 100 steps).
#
#   ./launch/local_fakepod.sh [num_devices] [extra overrides...]
set -euo pipefail
cd "$(dirname "$0")/.."

N="${1:-8}"; shift || true
export JAX_PLATFORMS=cpu
export PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="--xla_force_host_platform_device_count=${N} ${XLA_FLAGS:-}"

exec python -m tpu_resnet train --preset smoke \
    train.train_dir=/tmp/tpu_resnet/fakepod \
    train.global_batch_size=$((N * 2)) \
    "$@"
