#!/usr/bin/env bash
# Multi-PROCESS fake cluster on one machine: P processes × D virtual CPU
# devices rendezvous via jax.distributed on a local port — the TPU-native
# analog of the reference's localhost ps/worker cluster
# (mkl-scripts/submit_mac_dist.sh: 1 ps + 2 workers on ports 2230/2220+).
# Validates the real multi-host code path (coordinator rendezvous,
# per-process input shards, cross-process all-reduce) with zero hardware.
#
#   ./launch/local_multiprocess.sh [P] [D] [extra overrides...]
set -euo pipefail
cd "$(dirname "$0")/.."

P="${1:-2}"; shift || true
D="${1:-4}"; shift || true
PORT=$((20000 + RANDOM % 20000))
LOGDIR="${LOGDIR:-/tmp/tpu_resnet/multiproc}"
mkdir -p "$LOGDIR"

pids=()
for ((i = 0; i < P; i++)); do
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
  XLA_FLAGS="--xla_force_host_platform_device_count=${D}" \
  TPU_COORDINATOR_ADDRESS="127.0.0.1:${PORT}" \
  TPU_NUM_PROCESSES="$P" \
  TPU_PROCESS_ID="$i" \
  python -m tpu_resnet train --preset smoke \
      train.train_dir="$LOGDIR/run" \
      train.global_batch_size=$((P * D * 2)) \
      "$@" > "$LOGDIR/proc.$i.log" 2>&1 &
  pids+=($!)
done
echo "launched $P processes (logs: $LOGDIR/proc.*.log)"
code=0
for pid in "${pids[@]}"; do wait "$pid" || code=$?; done
exit $code
