#!/usr/bin/env bash
# Multi-PROCESS fake cluster on one machine: P processes × D virtual CPU
# devices rendezvous via jax.distributed on a local port — the TPU-native
# analog of the reference's localhost ps/worker cluster
# (mkl-scripts/submit_mac_dist.sh: 1 ps + 2 workers on ports 2230/2220+).
# Validates the real multi-host code path (coordinator rendezvous,
# per-process input shards, cross-process all-reduce) with zero hardware.
# Also the zero1 rehearsal vehicle (docs/PARALLELISM.md): pass
# mesh.partition=zero1 as an override to drill cross-replica optimizer
# sharding across real process boundaries.
#
#   ./launch/local_multiprocess.sh [P] [D] [extra overrides...]
set -euo pipefail
cd "$(dirname "$0")/.."

P="${1:-2}"; shift || true
D="${1:-4}"; shift || true
# Probe for a FREE port instead of rolling RANDOM: a collision with any
# listener (or a previous rehearsal's surviving coordinator) used to
# hang every process in rendezvous until the distributed-init timeout.
# The kernel hands out an unused ephemeral port; the tiny bind-to-launch
# race window is harmless next to a 1-in-dozens collision per run.
PORT=$(python3 -c 'import socket; s = socket.socket();
s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()')
LOGDIR="${LOGDIR:-/tmp/tpu_resnet/multiproc}"
mkdir -p "$LOGDIR"

pids=()
for ((i = 0; i < P; i++)); do
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
  XLA_FLAGS="--xla_force_host_platform_device_count=${D}" \
  TPU_COORDINATOR_ADDRESS="127.0.0.1:${PORT}" \
  TPU_NUM_PROCESSES="$P" \
  TPU_PROCESS_ID="$i" \
  python -m tpu_resnet train --preset smoke \
      train.train_dir="$LOGDIR/run" \
      train.global_batch_size=$((P * D * 2)) \
      "$@" > "$LOGDIR/proc.$i.log" 2>&1 &
  pids+=($!)
done
echo "launched $P processes on port $PORT (logs: $LOGDIR/proc.*.log)"

# Fail fast: the first nonzero exit kills the survivors instead of
# leaving them wedged in a dead collective until the full timeout set
# drains (one crashed process means the rendezvous group is already
# broken — the others can only hang or crash later).
code=0
remaining=$P
while ((remaining > 0)); do
  rc=0
  wait -n || rc=$?
  if ((rc == 0)); then
    remaining=$((remaining - 1))
    continue
  fi
  code=$rc
  echo "a process exited rc=$code — killing $((remaining - 1)) survivor(s)" >&2
  kill "${pids[@]}" 2>/dev/null || true
  wait || true
  break
done
exit $code
