"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: ResNet-50 CIFAR-10 training steps/sec at global batch 128
on the available chips — directly comparable to the reference's published
'local' number: 13.94 steps/s, README.md:28 (BASELINE.md row 1), which is
``vs_baseline``'s denominator. A second entry times the ImageNet-shaped
workload (ResNet-50 @ 224x224, batch 128, bf16) against the reference's
single-node 1ps-1wk b128 line (0.96 steps/s, README.md:48) and reports MFU
(measured train-step FLOPs over the chip's peak).

The measured step is the full training step: on-device augmentation
(pad/crop/flip/standardize), bf16 forward/backward, L2-in-loss, momentum
update, BN stats update — i.e. what the reference's
``mon_sess.run(train_op)`` covered (resnet_cifar_train.py:343-344), input
included. The CIFAR input edge is the framework's device-resident path
(tpu_resnet/data/device_data.py): the training split lives in HBM, batches
are cut on-device, and ``train.steps_per_call`` steps run per dispatch —
the same configuration a real CIFAR training run uses by default.
Synthetic data is used so the benchmark needs no dataset download; the
compute path is identical.

Robustness (round-1 postmortem: the TPU plugin hung/failed and the bench
died with a raw traceback and no JSON; round-2 postmortem: the tunnel was
down at the driver's capture time but live mid-round): the parent process
never imports jax. It WATCHES for the backend — cheap short-timeout
probes polled — and runs the measurement child the moment a probe
succeeds, so a flaky tunnel's live window is caught rather than
forfeited. On an exhausted window it falls back to a small CPU
measurement clearly labeled ``"backend": "cpu"``.

Driver-capture protocol (round-4 postmortem: BENCH_r04 recorded rc=124
with the one JSON line truncated mid-string in the driver's bounded tail
— the line carried a full inlined TPU snapshot and was only emitted at
parent-SIGTERM time):

- ``BENCH_WATCH_WINDOW`` (default 1500 s) is the TOTAL budget: probing,
  children, fallback AND the final emit all complete inside it, so the
  normal path is a clean ``exit 0`` — never the SIGTERM handler.
- Every emitted line is SMALL (~1 KB): on a non-TPU emit the newest
  archived chip artifact is attached as a compact ``cached_tpu_snapshot``
  summary (headline numbers + provenance), with the full snapshot written
  to ``docs/runs/cached_tpu_snapshot_emit.json`` instead of inlined.
- On the first failed probe a provisional line (``"provisional": true``)
  is emitted immediately, so even a driver timeout shorter than the
  window leaves one complete parseable line in a bounded stdout tail;
  the final line, printed last, supersedes it. ``BENCH_PROVISIONAL=0``
  disables this (used by wrappers that parse whole-file JSON).
- Exit code is 0 whenever a final JSON line was emitted; consumers judge
  quality by ``backend``/``partial`` fields, not by rc
  (tools/battery.d/10_bench.sh does exactly that).

    python bench.py                 # orchestrate (the driver's entry)
    python bench.py --child tpu     # measurement child, ambient backend
    python bench.py --child cpu     # measurement child, reduced counts
"""

import glob
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_CIFAR_SPS = 13.94     # reference README.md:28 (local b128)
BASELINE_IMAGENET_SPS = 0.96   # reference README.md:48 (1ps-1wk b128)

HEADLINE_METRIC = "cifar10_resnet50_train_steps_per_sec_b128"

def _print_line(text: str) -> None:
    """Emit one stdout line as a SINGLE write + flush. ``print`` may split
    string and newline across writes, so a SIGKILL could land between them
    and leave a complete-looking line that is actually mid-record; a
    single small write is atomic on pipes (< PIPE_BUF), so a killed
    emitter leaves either the whole line or a truncated one the salvage
    parser (`_parse_result`) skips — never a corrupt-but-parseable one
    (round-4 postmortem: BENCH_r04 captured rc=124 with parsed=null)."""
    sys.stdout.write(text + "\n")
    sys.stdout.flush()


def _peak_flops(device_kind: str):
    """Peak dense bf16 FLOP/s per chip — the shared table now lives with
    the MFU accounting layer (tpu_resnet/obs/mfu.py, jax-free import);
    BENCH_PEAK_FLOPS still overrides. Imported lazily so the parent
    orchestrator keeps its no-package-import startup path."""
    from tpu_resnet.obs.mfu import peak_flops_per_chip

    return peak_flops_per_chip(device_kind)


def _hbm_bytes(device_kind: str):
    """HBM capacity per chip — the peak-FLOPs table's memory twin
    (tpu_resnet/obs/memory.py, jax-free import; TPU_RESNET_HBM_BYTES
    overrides). Lets bench report hbm_utilization next to MFU on chips
    whose memory_stats() reports usage but no bytes_limit."""
    from tpu_resnet.obs.memory import hbm_bytes_per_chip

    return hbm_bytes_per_chip(device_kind)


def _hbm_snapshot(device_kind: str):
    """Post-measurement HBM utilization from live device stats
    (obs/memory.py sample_device_memory): peak bytes vs the reported or
    table capacity. {} on backends without memory_stats (CPU) — bench
    lines then simply omit the hbm fields, like mfu without a peak."""
    from tpu_resnet.obs.memory import sample_device_memory

    sample = sample_device_memory()
    if not sample:
        return {}
    out = {"hbm_bytes_peak": int(sample["hbm_bytes_peak"])}
    limit = sample.get("hbm_bytes_limit") or _hbm_bytes(device_kind)
    if limit:
        out["hbm_bytes_limit"] = int(limit)
        out["hbm_utilization"] = round(
            sample["hbm_bytes_peak"] / limit, 4)
    return out


# --------------------------------------------------------------------------
# measurement children (import jax; run under the parent's timeouts)
# --------------------------------------------------------------------------

def _build_train_setup(mesh, preset, resnet_size, batch, dtype, image,
                       synthetic=False, width=None, num_classes=None,
                       mutate_cfg=None):
    """Shared measurement scaffolding: resolved config + model + schedule
    + replicated initial state (one copy of what every measurement
    needs). ``None`` overrides keep the preset's values; ``synthetic``
    swaps the dataset for download-free data with the same class count
    (unless ``num_classes`` overrides it). ``mutate_cfg`` (cfg -> None)
    applies arbitrary overrides after the named ones — the hook
    tools/fused_model_ab.py uses to flip ``model.fused_blocks``."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet.config import load_config
    from tpu_resnet import parallel
    from tpu_resnet.models import build_model
    from tpu_resnet.train import build_schedule, init_state

    cfg = load_config(preset)
    if synthetic:
        classes = num_classes or cfg.data.num_classes
        cfg.data.dataset = "synthetic"
        cfg.data.synthetic_classes = classes
    elif num_classes is not None and num_classes != cfg.data.num_classes:
        raise ValueError(f"num_classes={num_classes} conflicts with "
                         f"preset {preset!r} ({cfg.data.num_classes})")
    cfg.data.image_size = image
    cfg.train.global_batch_size = batch
    if resnet_size is not None:
        cfg.model.resnet_size = resnet_size
    if width is not None:
        cfg.model.width_multiplier = width
    cfg.model.compute_dtype = dtype
    if mutate_cfg is not None:
        mutate_cfg(cfg)

    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    rng = jax.random.PRNGKey(0)
    state = init_state(model, cfg.optim, sched, rng,
                       jnp.zeros((1, image, image, 3)))
    state = jax.device_put(state, parallel.replicated(mesh))
    return cfg, model, sched, state, rng


def _fetch_sync(x) -> float:
    """Timing barrier that cannot lie: fetch the scalar to the host.

    ``jax.block_until_ready`` was observed returning early on a degrading
    remote-attached (axon-tunnel) backend — the r3 resident sweep recorded
    a physically impossible 20,829 st/s (≈ the dispatch-enqueue rate)
    because readiness resolved before the compute chain actually ran, and
    r2's streaming 584.3 st/s headline entry is retracted for the same
    reason (docs/PERF.md). A device→host copy of the result scalar cannot
    complete before every step it depends on, so every timed loop closes
    over this instead."""
    import jax
    import numpy as np
    return float(np.asarray(jax.device_get(x)))


def _measure_cifar(mesh, plans, preset="cifar10", resnet_size=None,
                   batch=128, dtype="bfloat16", split=50_000, width=None,
                   num_classes=None, mutate_cfg=None, breakdown_out=None):
    """Resident-path CIFAR-shaped measurement over one shared setup; model
    and optimizer come from ``preset`` (overridable for smoke tests).

    ``plans`` is a list of (steps_per_call, warmup_chunks, measure_chunks);
    each plan starts at an epoch boundary and must fit within one epoch
    (compile_resident_steps' no-boundary-crossing contract). Returns
    {steps_per_call: steps/sec}. ``breakdown_out`` (a dict) gains
    ``compile_seconds`` — the fetch-synced wall time of the first dispatch
    (trace + XLA compile + first chunk), the same number a real run
    reports via tpu_resnet/obs/breakdown.py."""
    import jax

    from tpu_resnet.data import cifar as cifar_data
    from tpu_resnet.data import device_data
    from tpu_resnet.data.augment import get_augment_fns
    from tpu_resnet.train.step import make_train_step

    cfg, model, sched, state, rng = _build_train_setup(
        mesh, preset, resnet_size=resnet_size, batch=batch, dtype=dtype,
        image=32, synthetic=True, width=width, num_classes=num_classes,
        mutate_cfg=mutate_cfg)

    # CIFAR-sized synthetic split, resident in HBM like a real run.
    images, labels = cifar_data.synthetic_data(split, 32,
                                               cfg.data.num_classes)
    ds = device_data.DeviceDataset(mesh, images, labels,
                                   cfg.train.global_batch_size, seed=0)
    augment_fn, _ = get_augment_fns("cifar10")
    run_chunk = device_data.compile_resident_steps(
        make_train_step(model, cfg.optim, sched, cfg.data.num_classes,
                        augment_fn, base_rng=rng, mesh=mesh), ds, mesh,
        max(k for k, _, _ in plans))

    spe = ds.steps_per_epoch
    results = {}
    step = 0
    first_t0 = time.perf_counter()
    first_dispatch = True
    for k, warmup_chunks, measure_chunks in plans:
        if warmup_chunks < 1:
            raise ValueError(f"plan k={k}: warmup_chunks must be >= 1 "
                             "(the timed loop reads the warmed metrics)")
        if measure_chunks < 1:
            raise ValueError(f"plan k={k}: measure_chunks must be >= 1 "
                             "(zero measured chunks would report 0 st/s "
                             "as a real number)")
        if (warmup_chunks + measure_chunks) * k > spe:
            raise ValueError(f"plan k={k} spans more than one epoch")
        step = -(-step // spe) * spe  # align to the next epoch boundary
        for _ in range(warmup_chunks):
            state, metrics = run_chunk(state, step, k)
            step += k
            if first_dispatch:
                first_dispatch = False
                _fetch_sync(metrics["loss"])
                if breakdown_out is not None:
                    breakdown_out["compile_seconds"] = round(
                        time.perf_counter() - first_t0, 3)
        _fetch_sync(metrics["loss"])

        t0 = time.perf_counter()
        for _ in range(measure_chunks):
            state, metrics = run_chunk(state, step, k)
            step += k
        _fetch_sync(metrics["loss"])
        results[k] = measure_chunks * k / (time.perf_counter() - t0)
    return results


def _measure_cifar_streaming(mesh, warmup_super, measure_super, stage=8,
                             resnet_size=50, batch=128,
                             dtype="bfloat16", split=50_000):
    """CIFAR through the *streaming* input edge (host batcher → staged
    superbatch transfers → fused dispatch) — the path multi-host and
    ImageNet runs use. Comparable to the same 13.94 baseline: the
    reference's step also included its host input pipeline. Returns
    ``(steps/sec, breakdown)`` where breakdown is the measured window's
    data_wait/dispatch decomposition (tpu_resnet/obs/breakdown.py) — the
    bench line answers "was this measurement input-bound" directly."""
    import jax
    import numpy as np

    from tpu_resnet.obs import StepBreakdown

    from tpu_resnet import parallel
    from tpu_resnet.data import device_data, pipeline
    from tpu_resnet.data import cifar as cifar_data
    from tpu_resnet.data.augment import get_augment_fns
    from tpu_resnet.train.step import make_train_step

    cfg, model, sched, state, rng = _build_train_setup(
        mesh, "cifar10", resnet_size=resnet_size, batch=batch, dtype=dtype,
        image=32, synthetic=True)

    images, labels = cifar_data.synthetic_data(split, 32, 10)
    batcher = pipeline.ShardedBatcher(images, labels.astype(np.int32),
                                      batch, seed=0, process_index=0,
                                      process_count=1)
    host_iter = pipeline.BackgroundIterator(iter(batcher),
                                            capacity=2 * stage + 2)
    it = pipeline.staged_superbatch_prefetch(
        host_iter, parallel.staged_batch_sharding(mesh), stage=stage)
    augment_fn, _ = get_augment_fns("cifar10")
    run = device_data.compile_staged_stream_steps(
        make_train_step(model, cfg.optim, sched, 10, augment_fn,
                        base_rng=rng, mesh=mesh), mesh)

    try:
        for _ in range(warmup_super):
            gi, gl, k = next(it)
            state, metrics = run(state, gi, gl, 0, k)
        _fetch_sync(metrics["loss"])

        bd = StepBreakdown()
        t0 = time.perf_counter()
        measured = 0
        for _ in range(measure_super):
            with bd.data_wait():
                gi, gl, k = next(it)
            with bd.dispatch():
                state, metrics = run(state, gi, gl, 0, k)
            measured += k
        _fetch_sync(metrics["loss"])
        return measured / (time.perf_counter() - t0), bd.interval()
    finally:
        it.close()          # drop the depth-2 staged device buffers
        host_iter.close()   # release the producer thread + host split


def _train_step_flops(compiled):
    """Per-step, per-device FLOPs from XLA's compiled cost analysis (the
    post-SPMD module is per-device); None if the backend doesn't report
    them. Extraction shared with the live gauges (obs/mfu.py)."""
    from tpu_resnet.obs.mfu import program_flops

    try:
        return program_flops(compiled.cost_analysis())
    except Exception:
        return None


def _train_step_comms(compiled, mesh):
    """Bench fields from the compiled step's collective summary
    (obs/comms.py over the post-partitioner HLO): per-device
    bytes-on-wire per step (the perfwatch sweep-comm series,
    lower-is-better), collective count and — when the chip's ICI
    bandwidth is known — the predicted time-on-wire. {} if the backend
    reports no HLO; bench lines then omit the comms fields, like mfu
    without a peak."""
    from tpu_resnet.obs.comms import (comms_from_compiled, ici_bytes_per_chip,
                                      predicted_time_on_wire)

    try:
        shape = dict(mesh.shape)
        summary = comms_from_compiled(compiled, shape.get("data", 1),
                                      shape.get("model", 1))
    except Exception:
        return {}
    if summary is None:
        return {}
    out = {"comms_bytes_per_step": summary["wire_bytes_per_device"],
           "comms_collective_count": summary["collective_count"]}
    kind = mesh.devices.flat[0].device_kind
    if ici_bytes_per_chip(kind):
        out["predicted_time_on_wire_s"] = round(
            predicted_time_on_wire(summary, kind), 6)
    return out


def _measure_imagenet(mesh, warmup_steps, measure_steps, resnet_size=50,
                      batch=128, image=224, dtype="bfloat16",
                      stem_s2d=None, mutate_cfg=None):
    """ImageNet-shaped training step: ResNet-50 @ 224, batch 128, bf16,
    synthetic pre-processed input resident on device. Returns
    (steps/s, flops_per_step or None, comms bench fields — possibly {}).
    ``stem_s2d`` overrides model.stem_space_to_depth (None = config
    default) for the stem A/B; ``mutate_cfg`` as in
    ``_build_train_setup``."""
    import jax
    import numpy as np

    from tpu_resnet import parallel
    from tpu_resnet.train.step import make_train_step, shard_step

    cfg, model, sched, state, rng = _build_train_setup(
        mesh, "imagenet", resnet_size=resnet_size, batch=batch,
        dtype=dtype, image=image, mutate_cfg=mutate_cfg)
    if stem_s2d is not None and stem_s2d != cfg.model.stem_space_to_depth:
        from tpu_resnet.models import build_model
        cfg.model.stem_space_to_depth = stem_s2d
        model = build_model(cfg)  # same param tree either way

    # Pre-processed (VGG mean-subtracted) float input, as the host pipeline
    # would deliver it; one resident batch re-fed each step so the
    # measurement isolates the training step itself.
    bs = parallel.batch_sharding(mesh)
    images = jax.device_put(
        np.random.RandomState(0)
        .uniform(-114.0, 141.0, (batch, image, image, 3))
        .astype(np.float32), bs)
    labels = jax.device_put(
        np.random.RandomState(1).randint(0, 1000, batch)
        .astype(np.int32), bs)

    step_fn = shard_step(
        make_train_step(model, cfg.optim, sched, 1000, None,
                        base_rng=rng, mesh=mesh), mesh)
    # donate_state=True (the default, what train/loop.py runs): XLA may
    # update params in place instead of allocating a fresh state tree —
    # the measured step is the production configuration.
    compiled = step_fn.lower(state, images, labels).compile()
    flops = _train_step_flops(compiled)
    comms = _train_step_comms(compiled, mesh)

    for _ in range(warmup_steps):
        state, metrics = compiled(state, images, labels)
    _fetch_sync(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(measure_steps):
        state, metrics = compiled(state, images, labels)
    _fetch_sync(metrics["loss"])
    dt = time.perf_counter() - t0
    return measure_steps / dt, flops, comms


def _synthetic_photo_jpeg(size=(640, 480), quality=90, rng=None,
                          freqs=(8.0, 6.0)):
    """A photo-like test JPEG: smooth structure + mild noise compresses
    ~10:1 like real ImageNet photos. (Uniform noise — the old test image —
    is the pathological worst case: ~1.5:1, entropy-decode-bound, and made
    every decode-path optimization invisible.) Canonical implementation
    lives with the data engine (tpu_resnet/data/engine.py) so the bench,
    ``doctor --data-bench`` and tools/input_edge.py rest on the same
    entropy premise; this name is kept as the tools' import point."""
    from tpu_resnet.data.engine import synthetic_photo_jpeg

    return synthetic_photo_jpeg(size=size, quality=quality, rng=rng,
                                freqs=freqs)


def _measure_host_decode(n_images=200, size=(640, 480), engine_curve=True,
                         engine_secs=4.0):
    """Host-side JPEG decode + VGG preprocess throughput (images/s),
    native C++ (libjpeg-turbo partial decode + window resize) vs PIL, on
    the train path (random side 256-512 + random crop) and the eval path
    (side 256 + central crop) — the ImageNet input edge the reference
    bounded with 16 queue threads + num_parallel_calls=4
    (cifar_input.py:99-100, resnet_imagenet_train.py:170-171). Backend-
    independent; run per host."""
    import numpy as np

    from tpu_resnet.data.imagenet import decode_and_crop
    from tpu_resnet.native import jpeg_available

    jpeg = _synthetic_photo_jpeg(size)
    out = {"native_jpeg_built": bool(jpeg_available()),
           "jpeg_bytes": len(jpeg)}
    for label, use_native in (("native", True), ("pil", False)):
        for mode, train in (("train", True), ("eval", False)):
            d_rng = np.random.default_rng(1)
            decode_and_crop(jpeg, train, d_rng, use_native=use_native)
            t0 = time.perf_counter()
            for _ in range(n_images):
                decode_and_crop(jpeg, train, d_rng, use_native=use_native)
            rate = n_images / (time.perf_counter() - t0)
            out[f"{label}_{mode}_images_per_sec"] = round(rate, 1)
    out["native_images_per_sec"] = out["native_train_images_per_sec"]
    out["pil_images_per_sec"] = out["pil_train_images_per_sec"]
    out["native_speedup"] = round(
        out["native_images_per_sec"] / out["pil_images_per_sec"], 2)
    if engine_curve:
        # Process-engine worker-scaling curve (tpu_resnet/data/engine.py):
        # the multiprocess answer to the GIL wall this section measured —
        # BENCH_r04's 372 img/s single-host ceiling vs the chip's ~3032.
        # Same probe as `doctor --data-bench`, so a bench line and an
        # operator triage are directly comparable.
        try:
            from tpu_resnet.data.engine import decode_scaling_probe
            cpus = os.cpu_count() or 1
            out["engine_scaling"] = decode_scaling_probe(
                proc_counts=(1, min(8, cpus)), seconds=engine_secs)
        except Exception as e:  # the curve must never sink the section
            out["engine_scaling_error"] = f"{type(e).__name__}: {e}"[:300]
    return out


def _measure_record_split(n_records=400, record_bytes=60_000):
    """CRC32C-verified TFRecord shard read throughput (MB/s), native C++
    plane vs pure-python — the tf.data C++ reader role (SURVEY.md §2.4).
    Verified reads are the native plane's headline win (~200x measured);
    plain framing reads are memcpy-bound either way and reported too."""
    import os
    import tempfile

    import numpy as np

    from tpu_resnet.data import tfrecord
    from tpu_resnet.data.imagenet import read_shard_records

    rng = np.random.default_rng(0)
    payload = [rng.integers(0, 256, record_bytes, dtype=np.uint8).tobytes()
               for _ in range(8)]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "shard")
        tfrecord.write_records(
            path, [payload[i % 8] for i in range(n_records)])
        from tpu_resnet.native import available

        mb = os.path.getsize(path) / 1e6
        # Label honesty: without the built library the "native" cases
        # silently measure the python fallback.
        out = {"native_built": bool(available())}
        cases = (
            ("native_crc", lambda: read_shard_records(path, use_native=True,
                                                      verify_crc=True)),
            ("python_crc", lambda: tfrecord.read_records(path,
                                                         verify_crc=True)),
            ("native_plain", lambda: read_shard_records(path,
                                                        use_native=True)),
            ("python_plain", lambda: tfrecord.read_records(path)),
        )
        for label, fn in cases:
            sum(len(r) for r in fn())  # warm page cache
            t0 = time.perf_counter()
            n = sum(1 for _ in fn())
            dt = time.perf_counter() - t0
            assert n == n_records
            out[f"{label}_mb_per_sec"] = round(mb / dt, 1)
        out["native_crc_speedup"] = round(
            out["native_crc_mb_per_sec"] / out["python_crc_mb_per_sec"], 1)
        return out


def _measure_pallas_ab(iters=200):
    """A/B the Pallas fused softmax-xent (fwd+bwd) against the XLA/optax
    chain at b128x10 and b128x1000 (VERDICT round 1 item 6).

    The ``iters`` grad evaluations are fused into ONE dispatch with
    ``lax.scan`` (each iteration's input is perturbed by the running
    accumulator so XLA can neither hoist the loop-invariant computation
    nor overlap iterations) — per-dispatch command latency would otherwise
    swamp a ~µs kernel, especially on a remote-attached chip."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet.ops import softmax_xent_mean
    from tpu_resnet.train.step import softmax_xent

    out = {}
    for classes in (10, 1000):
        rng = jax.random.PRNGKey(classes)
        logits = jax.random.normal(rng, (128, classes), jnp.float32)
        labels = jax.random.randint(jax.random.PRNGKey(1), (128,), 0, classes)

        def time_fn(fn):
            g = jax.grad(fn)

            @jax.jit
            def many(x):
                def body(acc, _):
                    dx = g(x + acc * 1e-30)  # accumulator-dependent input
                    return acc + jnp.sum(dx), None

                acc, _ = jax.lax.scan(body, jnp.float32(0.0), None,
                                      length=iters)
                return acc

            _fetch_sync(many(logits))  # compile + warm
            t0 = time.perf_counter()
            _fetch_sync(many(logits))
            return (time.perf_counter() - t0) / iters * 1e6  # us

        pallas_us = time_fn(lambda x: softmax_xent_mean(x, labels))
        xla_us = time_fn(lambda x: softmax_xent(x, labels, classes))
        out[f"b128x{classes}"] = {
            "pallas_us": round(pallas_us, 2), "xla_us": round(xla_us, 2),
            "speedup": round(xla_us / pallas_us, 3)}
    return out


# Rough per-section wall-time estimates (seconds, cache-cold TPU child —
# r3 battery log) used by the child's wall-clock budget gate: a section
# that cannot finish before BENCH_CHILD_DEADLINE is skipped WITH a
# marker, so a tight budget degrades to fewer sections in a complete,
# parseable final line — never to a mid-print kill (round-4 postmortem:
# BENCH_r04 recorded rc=124, parsed=null).
_SECTION_EST = {
    "cifar_streaming": 120, "imagenet": 240, "imagenet_b2": 180,
    "imagenet_stem_ab": 180, "wrn28_10_cifar100": 150,
    "pallas_xent_ab": 90, "host_decode": 60, "record_split": 30,
}


def _child_deadline():
    """Absolute wall-clock deadline handed down by the parent
    (``BENCH_CHILD_DEADLINE``, epoch seconds); None = unbounded."""
    try:
        return float(os.environ.get("BENCH_CHILD_DEADLINE") or 0) or None
    except ValueError:
        return None


def _section_est(name: str) -> float:
    """Estimate for a section by its RESULT key — the secondary-ImageNet
    section's key embeds the configured batch (``imagenet_b256``), so it
    must normalize to the table's ``imagenet_b2`` row rather than fall
    through to the default (which under-gates it by 60s — enough to blow
    the parent's SIGKILL margin, the exact failure the gate prevents)."""
    if re.fullmatch(r"imagenet_b\d+", name):
        name = "imagenet_b2"
    return _SECTION_EST.get(name, 120)


def _section_fits(deadline, est_sec, now=None) -> bool:
    """Budget gate: can a section estimated at ``est_sec`` finish before
    ``deadline``? Pure so the skip policy is unit-testable."""
    if deadline is None:
        return True
    now = time.time() if now is None else now
    return now + est_sec <= deadline


def run_child(kind: str) -> None:
    """Run the measurements on the ambient backend; final stdout line is
    ``RESULT_JSON: {...}`` for the parent. Progress goes to stderr."""
    import jax

    from tpu_resnet import parallel

    devices = jax.devices()
    kinds = devices[0].device_kind
    print(f"[bench child] backend={jax.default_backend()} "
          f"devices={len(devices)} kind={kinds}", file=sys.stderr)
    if kind == "tpu" and devices[0].platform == "cpu":
        raise RuntimeError("TPU child got a CPU backend — refusing to run "
                           "TPU-scale measurement counts on CPU")
    mesh = parallel.create_mesh(None)

    result = {"backend": jax.default_backend(), "device_kind": kinds,
              "n_devices": len(devices)}
    errors = {}
    deadline = _child_deadline()

    def fits(name: str) -> bool:
        """Wall-clock budget gate for one section; a skip is recorded in
        the errors map so the final line says WHAT was dropped and why
        (silent truncation would read as 'covered everything')."""
        if _section_fits(deadline, _section_est(name)):
            return True
        errors[name] = ("skipped: section does not fit the remaining "
                        "wall-clock budget (BENCH_CHILD_DEADLINE)")
        print(f"[bench child] skipping {name}: budget exhausted",
              file=sys.stderr)
        return False

    def snapshot():
        """Emit the current result as a RESULT_JSON line. Later lines
        supersede earlier ones (the parent takes the last), so a child
        killed by a timeout mid-run still leaves its completed
        measurements on stdout for the parent to salvage."""
        snap = dict(result)
        if errors:
            snap["errors"] = dict(errors)
        _print_line("RESULT_JSON: " + json.dumps(snap))

    if kind == "cpu":
        # Reduced counts: the CPU number is a liveness fallback, not a
        # performance claim.
        bd = {}
        by_k = _measure_cifar(mesh, [(2, 1, 2)], breakdown_out=bd)
        result["cifar"] = {"steps_per_sec": round(by_k[2], 2), **bd}
    else:
        # The HEADLINE stays at steps_per_call=10 (comparable across
        # rounds); k=50 is reported alongside to show what more dispatch
        # fusion buys on this attachment (remote tunnels pay more per
        # dispatch). Both plans share one setup/compile cache.
        bd = {}
        by_k = _measure_cifar(mesh, [(10, 4, 30), (50, 2, 5)],
                              breakdown_out=bd)
        result["cifar"] = {
            "steps_per_sec": round(by_k[10], 2),
            "steps_per_call": 10,
            "by_steps_per_call": {k: round(v, 2)
                                  for k, v in by_k.items()},
            **bd,
        }
    print(f"[bench child] cifar: {result['cifar']}", file=sys.stderr)
    snapshot()

    if kind == "tpu":
        if fits("cifar_streaming"):
            try:
                s_sps, s_bd = _measure_cifar_streaming(mesh, warmup_super=2,
                                                       measure_super=12)
                result["cifar_streaming"] = {
                    "steps_per_sec": round(s_sps, 2),
                    "vs_baseline": round(s_sps / BASELINE_CIFAR_SPS, 2),
                    **s_bd}
                print(f"[bench child] cifar streaming: {s_sps:.2f} steps/s",
                      file=sys.stderr)
            except Exception as e:
                errors["cifar_streaming"] = f"{type(e).__name__}: {e}"[:500]
        snapshot()
        def imagenet_entry(sps, flops, batch):
            """steps/s + images/s + MFU from per-device FLOPs (XLA cost
            analysis, analytic ResNet-50 estimate as fallback)."""
            entry = {"value": round(sps, 3), "unit": "steps/sec",
                     "images_per_sec": round(sps * batch, 1)}
            if flops:
                entry["flops_per_step_per_device"] = flops
                entry["flops_source"] = "xla_cost_analysis"
            else:
                # Analytic: ResNet-50@224 fwd ~= 4.09 GF/img; train ~= 3x;
                # normalized per device like the cost-analysis branch.
                entry["flops_per_step_per_device"] = (
                    3 * 4.09e9 * batch / len(devices))
                entry["flops_source"] = "analytic"
            peak = _peak_flops(kinds)
            if peak:
                # peak is per chip, flops are per device → MFU per chip.
                entry["mfu"] = round(
                    entry["flops_per_step_per_device"] * sps / peak, 4)
                entry["peak_flops_assumed_per_chip"] = peak
            # HBM twin: peak device memory of the measurement just run
            # vs capacity — a knob that "wins" MFU by blowing the memory
            # budget shows it here (and perfwatch gates on it).
            entry.update(_hbm_snapshot(kinds))
            return entry

        if fits("imagenet"):
            try:
                inet_sps, flops, comms = _measure_imagenet(
                    mesh, warmup_steps=5, measure_steps=30)
                entry = imagenet_entry(inet_sps, flops, 128)
                entry.update(comms)
                entry["metric"] = \
                    "imagenet_resnet50_train_steps_per_sec_b128"
                entry["vs_baseline"] = round(
                    inet_sps / BASELINE_IMAGENET_SPS, 2)
                result["imagenet"] = entry
                print(f"[bench child] imagenet: {inet_sps:.3f} steps/s "
                      f"mfu={entry.get('mfu')}", file=sys.stderr)
            except Exception as e:
                errors["imagenet"] = f"{type(e).__name__}: {e}"[:500]
        snapshot()
        # Secondary ImageNet entry at a larger batch: the b128 line stays
        # the baseline-comparable headline; this one shows how utilization
        # scales when the MXU is given bigger tiles.
        try:
            b2 = int(os.environ.get("BENCH_IMAGENET_BATCH2") or "256")
        except ValueError:
            b2 = 0
        if b2 and fits(f"imagenet_b{b2}"):
            try:
                sps2, flops2, comms2 = _measure_imagenet(
                    mesh, warmup_steps=3, measure_steps=15, batch=b2)
                result[f"imagenet_b{b2}"] = imagenet_entry(sps2, flops2, b2)
                result[f"imagenet_b{b2}"].update(comms2)
                print(f"[bench child] imagenet b{b2}: {sps2:.3f} steps/s "
                      f"mfu={result[f'imagenet_b{b2}'].get('mfu')}",
                      file=sys.stderr)
            except Exception as e:
                errors[f"imagenet_b{b2}"] = f"{type(e).__name__}: {e}"[:500]
        snapshot()
        # Stem A/B: the space-to-depth stem (default ON, exact-equivalent
        # math) vs the plain 7x7/2 form — records what the optimization
        # buys on this chip at the headline batch.
        if fits("imagenet_stem_ab"):
            try:
                sps_plain, _, _ = _measure_imagenet(mesh, warmup_steps=3,
                                                    measure_steps=15,
                                                    stem_s2d=False)
                base = result.get("imagenet", {}).get("value")
                result["imagenet_stem_ab"] = {
                    "plain_stem_steps_per_sec": round(sps_plain, 3),
                    "s2d_stem_steps_per_sec": base,
                    "s2d_speedup": (round(base / sps_plain, 3)
                                    if base else None)}
                print(f"[bench child] stem A/B: "
                      f"{result['imagenet_stem_ab']}", file=sys.stderr)
            except Exception as e:
                errors["imagenet_stem_ab"] = f"{type(e).__name__}: {e}"[:500]
        snapshot()
        # BASELINE.json config 4: Wide-ResNet-28-10 CIFAR-100 b128 — the
        # reference's wide-variant exercise, no published speed line (the
        # entry records our absolute number for cross-round tracking).
        if fits("wrn28_10_cifar100"):
            try:
                wrn_batch = 128
                wrn = _measure_cifar(mesh, [(10, 2, 10)],
                                     preset="wrn28_10_cifar100",
                                     batch=wrn_batch)
                result["wrn28_10_cifar100"] = {
                    "steps_per_sec": round(wrn[10], 2),
                    "images_per_sec": round(wrn[10] * wrn_batch, 1)}
                print(f"[bench child] wrn28-10: {wrn[10]:.2f} steps/s",
                      file=sys.stderr)
            except Exception as e:
                errors["wrn28_10_cifar100"] = \
                    f"{type(e).__name__}: {e}"[:500]
        snapshot()
        if fits("pallas_xent_ab"):
            try:
                result["pallas_xent_ab"] = _measure_pallas_ab()
                print(f"[bench child] pallas A/B: "
                      f"{result['pallas_xent_ab']}", file=sys.stderr)
            except Exception as e:
                errors["pallas_xent_ab"] = f"{type(e).__name__}: {e}"[:500]
        snapshot()
        if fits("host_decode"):
            try:
                result["host_decode"] = _measure_host_decode()
                print(f"[bench child] host decode: "
                      f"{result['host_decode']}", file=sys.stderr)
            except Exception as e:
                errors["host_decode"] = f"{type(e).__name__}: {e}"[:500]
        snapshot()
        if fits("record_split"):
            try:
                result["record_split"] = _measure_record_split()
                print(f"[bench child] record split: "
                      f"{result['record_split']}", file=sys.stderr)
            except Exception as e:
                errors["record_split"] = f"{type(e).__name__}: {e}"[:500]

    snapshot()


# --------------------------------------------------------------------------
# parent orchestration (never imports jax)
# --------------------------------------------------------------------------

def _run(cmd, env, timeout):
    try:
        proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=timeout,
                              cwd=os.path.dirname(os.path.abspath(__file__)))
        return proc.returncode, proc.stdout
    except subprocess.TimeoutExpired as e:
        out = e.stdout or ""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out + f"\n[parent] timeout after {timeout}s"


def _probe_tpu(timeout):
    """Can the ambient backend initialize at all? Short-timeout subprocess
    so a hanging PJRT plugin costs seconds, not the driver's budget."""
    code = ("import jax; d = jax.devices(); "
            "print('PROBE_OK', len(d), '|', d[0].device_kind, '|', "
            "d[0].platform, jax.default_backend())")
    rc, out = _run([sys.executable, "-c", code], dict(os.environ), timeout)
    last = out.strip().splitlines()[-1] if out.strip() else f"rc={rc}"
    # A silent CPU fallback must not pass as "TPU available" — the
    # TPU-scale child would burn its whole timeout on CPU. Accept only a
    # non-cpu accelerator backend (tpu, or a PJRT plugin name like 'axon').
    ok = (rc == 0 and "PROBE_OK" in last
          and " cpu" not in last.rsplit("|", 1)[-1])
    return ok, last


def _parse_result(out: str):
    """Last *intact* RESULT_JSON snapshot — a child killed mid-print (the
    timeout-salvage case) can truncate its final line, in which case the
    previous snapshot wins."""
    for line in reversed(out.splitlines()):
        if line.startswith("RESULT_JSON: "):
            try:
                return json.loads(line[len("RESULT_JSON: "):])
            except ValueError:
                continue
    return None


def _cached_tpu_snapshot():
    """Latest archived real-TPU bench artifact, for carrying chip truth
    through a down tunnel (VERDICT r3 item 3: every official BENCH_r0N so
    far was captured while the flapping tunnel was down, recording 0.01
    st/s CPU fallbacks while fetch-verified TPU numbers sat in docs/runs/).
    Scans ``docs/runs/bench_r*_tpu_v5e.json`` — artifacts archived by the
    battery only after validating ``backend == "tpu" and not partial`` —
    and returns the newest with explicit provenance. Clearly labeled: this
    is NOT a measurement of the current run."""
    here = os.path.dirname(os.path.abspath(__file__))
    cands = []
    for p in glob.glob(os.path.join(here, "docs", "runs",
                                    "bench_r*_tpu_v5e.json")):
        m = re.search(r"bench_r(\d+)_tpu_v5e\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    for rnd, p in sorted(cands, reverse=True):
        try:
            with open(p) as f:
                snap = json.load(f)
        except (ValueError, OSError):
            continue
        if snap.get("backend") != "tpu" or snap.get("partial"):
            continue
        try:
            head = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=here,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, timeout=10).stdout.strip() or None
        except Exception:
            head = None
        # Provenance timestamp: prefer the measurement-time stamp recorded
        # inside the artifact (written by _emit_tpu since r5); a file mtime
        # is checkout time after a fresh clone, so when falling back to it
        # the field says so (ADVICE r4).
        if snap.get("captured_at"):
            archived_at = snap["captured_at"]
            archived_at_source = "captured_at"
        else:
            archived_at = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(p)))
            archived_at_source = "file_mtime"
        return {
            "provenance": ("cached real-TPU measurement from an earlier "
                           "live tunnel window; NOT measured in this run "
                           "(chip unreachable — see tpu_error/error)"),
            "source_file": os.path.relpath(p, here),
            "archived_round": rnd,
            "archived_at": archived_at,
            "archived_at_source": archived_at_source,
            "emitting_head": head,
            "snapshot": snap,
        }
    return None


def _cached_summary(cached: dict):
    """Compact inline form of a cached TPU artifact, sized for a driver's
    bounded stdout tail (round-4 postmortem: inlining the full snapshot
    made the one JSON line ~3 KB and it arrived truncated — parsed=null).
    The full snapshot is written beside the other run artifacts (atomic
    rename — concurrent bench processes must not tear it, and every emit
    writes so the referenced file always matches the inline summary) and
    only referenced here."""
    snap = cached["snapshot"]
    here = os.path.dirname(os.path.abspath(__file__))
    full_rel = os.path.join("docs", "runs", "cached_tpu_snapshot_emit.json")
    try:
        tmp = os.path.join(here, full_rel + f".tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(cached, f, indent=1)
        os.replace(tmp, os.path.join(here, full_rel))
    except OSError:
        full_rel = None
    summary = {
        "provenance": cached["provenance"],
        "source_file": cached["source_file"],
        "archived_round": cached["archived_round"],
        "archived_at": cached["archived_at"],
        "archived_at_source": cached["archived_at_source"],
        "emitting_head": cached["emitting_head"],
        "metric": snap.get("metric"),
        "value": snap.get("value"),
        "unit": snap.get("unit"),
        "vs_baseline": snap.get("vs_baseline"),
        "device_kind": snap.get("device_kind"),
        "full_snapshot_file": full_rel,
    }
    imagenet = snap.get("imagenet") or {}
    if imagenet:
        summary["imagenet_steps_per_sec"] = imagenet.get("value")
        summary["imagenet_mfu"] = imagenet.get("mfu")
    return summary


def _emit(result: dict, cifar_sps, extra=None):
    """Print the single driver-facing JSON line (headline = CIFAR). Any
    emit that is not a live-TPU measurement (CPU fallback, SIGTERM flush,
    backend=none) additionally carries the newest archived real-TPU
    artifact under ``cached_tpu_snapshot`` so a down tunnel degrades the
    record to "last chip truth + today's failure diagnostics" instead of
    an uncontextualized 0.01 st/s."""
    line = {
        "metric": HEADLINE_METRIC,
        "value": round(cifar_sps, 2) if cifar_sps else None,
        "unit": "steps/sec",
        "vs_baseline": (round(cifar_sps / BASELINE_CIFAR_SPS, 2)
                        if cifar_sps else None),
    }
    line.update(result)
    if extra:
        line.update(extra)
    if line.get("backend") != "tpu":
        cached = _cached_tpu_snapshot()
        if cached:
            line["cached_tpu_snapshot"] = _cached_summary(cached)
    _print_line(json.dumps(line))


def _clip(s: str, limit: int = 500) -> str:
    """Bound a diagnostic string while keeping its TAIL — the newest
    entries (give-up reason, latest child/probe failure) are appended
    last and are the ones worth preserving (review finding r5)."""
    return s if len(s) <= limit else "…" + s[-(limit - 1):]


def _salvage(result, rc, how_died):
    """Mark a snapshot from a child that didn't exit cleanly. Completed
    sections are valid regardless of how the child later died (timeout,
    segfault, OOM-kill) — a later failure doesn't invalidate measurements
    that already ran; losing them is the failure mode the incremental
    snapshots exist to prevent."""
    if rc != 0:
        result["partial"] = True
        result.setdefault("errors", {})["child_exit"] = (
            f"{how_died}; entries after the last snapshot are missing")
    return result


def _completeness(result):
    """How many measurement sections a TPU snapshot completed — used to
    prefer the most complete snapshot across child attempts."""
    meta = {"backend", "device_kind", "n_devices", "errors", "partial"}
    return len([k for k in result if k not in meta])


def _emit_tpu(result, rc, how_died, provisional=False):
    result = _salvage(dict(result), rc, how_died)
    # Measurement-time stamp, carried into archived artifacts so cached
    # emits can report when the number was captured (not a file mtime).
    result.setdefault("captured_at", time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    if provisional:
        result["provisional"] = True
    cifar = result.pop("cifar", {})
    if len(cifar) > 1:  # keep per-k detail beside the headline
        result["cifar_detail"] = cifar
    _emit(result, cifar.get("steps_per_sec"))


def main():
    """Long-window watcher orchestration (round-2 postmortem: the tunnel to
    the chip flaps, with live windows the old fixed two-probe schedule
    missed entirely — BENCH_r02 forfeited to a CPU fallback while a live
    window mid-round had measured 206+ steps/s). Poll with cheap
    short-timeout probes and run the measurement child the moment the
    backend is live. A clean child emits immediately; a crashed/timed-out
    child's partial snapshot is kept as a fallback but retried while
    window and attempts remain, preferring the most complete snapshot
    across attempts.

    ``BENCH_WATCH_WINDOW`` is the TOTAL runtime budget (round-4
    postmortem: the old watch loop always outlived the driver's own
    timeout on a down tunnel, so the only emit path was the SIGTERM
    handler and the recorded rc was 124). Probing, child attempts, the
    CPU fallback and the final emit are each admitted only if they fit
    before the hard deadline minus an emit margin; the normal path on any
    tunnel state is a clean exit 0 with one small final JSON line."""
    max_children = int(os.environ.get("BENCH_TPU_ATTEMPTS", "3"))
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
    poll_sleep = int(os.environ.get("BENCH_POLL_SLEEP", "45"))
    # The 2100 s child cap exceeds the default 1500 s total budget on
    # purpose: a live-at-first-probe run gets eff_timeout ~1395 s, and a
    # full cache-cold measurement child measured ~840 s (r3 battery log)
    # — the cap only bites pathological runs, and a timeout-killed child
    # still salvages every completed section.
    child_timeout = int(os.environ.get("BENCH_CHILD_TIMEOUT", "2100"))
    window = int(os.environ.get("BENCH_WATCH_WINDOW", "1500"))
    margin = int(os.environ.get("BENCH_EMIT_MARGIN", "30"))
    # 0 = poll until the budget runs out (the driver's standalone mode).
    # An outer watcher that owns polling itself (tools/battery.d/10_bench.sh
    # runs with a child-sized budget) sets a small cap so a tunnel that died
    # between its probe and ours returns to ITS poll loop in minutes instead
    # of nesting a ~45-min watch inside the battery stage.
    max_probe_fails = int(os.environ.get("BENCH_MAX_PROBE_FAILS", "0"))
    hard_deadline = time.time() + window

    def fits(need_s: float) -> bool:
        """Admit a step only if it can finish before the hard deadline
        with the emit margin intact."""
        return time.time() + need_s + margin < hard_deadline

    def headroom() -> float:
        return hard_deadline - time.time() - margin

    diags = []
    best = None         # (completeness, result, rc, how_died)
    children = probes = 0
    cpu_stash_tried = False
    provisional_emitted = False
    cpu_timeout = max(600, child_timeout // 2)

    # The driver's own timeout is unknown: if it SIGTERMs the watcher
    # mid-window, emit the best snapshot so far (or at least the probe
    # diagnostics) instead of dying with no JSON line at all. The handler
    # is DISARMED right before any final emit so a late SIGTERM can never
    # print a second, contradictory JSON line or flip the exit code.
    import signal

    phase = {"name": "watch window"}
    cpu_stash = {}      # pre-computed CPU fallback (a real number to emit
                        # even if SIGTERMed mid-watch)

    def _emit_cpu(result, note):
        result = dict(result)
        cifar_sps = result.pop("cifar", {}).get("steps_per_sec")
        _emit(result, cifar_sps,
              extra={"tpu_error": note + _clip("; ".join(diags))})

    def _on_term(signum, frame):
        # Backstop only — the bounded budget means the normal path emits
        # and exits 0 before any sane parent timeout fires.
        if best:
            _emit_tpu(best[1], best[2], best[3] + "; parent SIGTERMed")
        elif cpu_stash:
            _emit_cpu(cpu_stash, f"SIGTERM during {phase['name']}; ")
        else:
            _emit({"backend": "none",
                   "error": (f"SIGTERM during {phase['name']}; "
                             + _clip("; ".join(diags)))}, None)
        sys.exit(0)

    def _disarm():
        signal.signal(signal.SIGTERM, signal.SIG_DFL)

    signal.signal(signal.SIGTERM, _on_term)

    me = os.path.abspath(__file__)
    while children < max_children and fits(probe_timeout):
        ok, diag = _probe_tpu(probe_timeout)
        probes += 1
        if len(diags) < 40:
            diags.append(f"probe{probes}: {diag}")
        print(f"[bench] probe {probes}: {'ok' if ok else 'down'} ({diag}); "
              f"budget remaining {int(headroom())}s", file=sys.stderr)
        if not ok:
            # A bounded stdout tail only keeps the LAST bytes: put one
            # complete small JSON line on stdout NOW so a parent timeout
            # shorter than our budget still captures a parseable record
            # (the final line, printed last, supersedes it for any
            # consumer that takes the last parseable line — the driver's
            # observed behavior in BENCH_r03).
            if (not provisional_emitted
                    and os.environ.get("BENCH_PROVISIONAL", "1") != "0"):
                provisional_emitted = True
                _emit({"backend": "none", "provisional": True,
                       "error": ("tunnel down at first probe; final "
                                 "line follows; "
                                 + _clip("; ".join(diags)))},
                      None)
            # After the first failed probe, pre-compute the CPU fallback
            # ONCE (a few minutes) so EVERY exit path — window exhausted,
            # driver SIGTERM — emits a real measurement, never just
            # diagnostics. One attempt only (a crashing CPU child must
            # not eat the watch window), and only with enough headroom
            # that a live TPU flap AFTER the precompute still gets a
            # meaningful child (review finding r5: a precompute admitted
            # into a tight budget left later flaps <60s of headroom).
            # Skipped when an outer watcher owns fallback policy
            # (BENCH_CPU_FALLBACK=0). The cached_tpu_snapshot summary
            # carries chip truth either way, so skipping is cheap.
            if (not cpu_stash and not cpu_stash_tried
                    and os.environ.get("BENCH_CPU_FALLBACK", "1") != "0"
                    and fits(cpu_timeout + probe_timeout + 600)):
                cpu_stash_tried = True
                print("[bench] pre-computing CPU fallback measurement",
                      file=sys.stderr)
                from __graft_entry__ import _cpu_env
                rc, out = _run([sys.executable, me, "--child", "cpu"],
                               _cpu_env(1), cpu_timeout)
                stash = _parse_result(out)
                if stash:
                    cpu_stash.update(_salvage(stash, rc,
                                              f"cpu child rc={rc}"))
                    print("[bench] CPU fallback stashed", file=sys.stderr)
                else:
                    diags.append(f"cpu precompute: rc={rc}, tail="
                                 + " | ".join(
                                     out.strip().splitlines()[-2:]))
            if max_probe_fails and probes >= max_probe_fails \
                    and children == 0:
                diags.append(f"gave up after {probes} failed probes "
                             "(BENCH_MAX_PROBE_FAILS)")
                break
            if fits(poll_sleep + probe_timeout):
                time.sleep(poll_sleep)
                continue
            break
        children += 1
        # A live window found near the end of the budget still gets a
        # (shortened) child: sections snapshot incrementally, so even a
        # timeout-killed child salvages everything it completed.
        eff_timeout = int(min(child_timeout, headroom()))
        if eff_timeout < 60:
            diags.append(f"live at probe{probes} but only {eff_timeout}s "
                         "headroom — skipping child")
            break
        # The child gets an absolute wall-clock deadline slightly inside
        # its kill timeout: sections that no longer fit are SKIPPED with
        # a marker and the final line is flushed complete, instead of the
        # parent's SIGKILL truncating it mid-print (BENCH_r04: rc=124,
        # parsed=null).
        child_env = dict(os.environ)
        child_env["BENCH_CHILD_DEADLINE"] = str(
            time.time() + max(60, eff_timeout - 30))
        rc, out = _run([sys.executable, me, "--child", "tpu"],
                       child_env, eff_timeout)
        sys.stderr.write(out)
        result = _parse_result(out)
        if result and rc == 0:
            _disarm()
            _emit_tpu(result, rc, "clean")
            return 0
        how = f"tpu child rc={rc} after {eff_timeout}s budget"
        diags.append(f"child{children}: rc={rc}, tail="
                     + " | ".join(out.strip().splitlines()[-3:]))
        if result:
            score = _completeness(result)
            print(f"[bench] child {children} died ({how}) with "
                  f"{score} sections complete — "
                  f"{'kept' if not best or score > best[0] else 'dropped'}",
                  file=sys.stderr)
            if not best or score > best[0]:
                best = (score, result, rc, how)
                # Put the new best on stdout NOW as a provisional line: a
                # driver whose timeout fires during the NEXT attempt still
                # captures these completed sections as its last parseable
                # record (the final emit, printed last, supersedes).
                _emit_tpu(best[1], best[2],
                          best[3] + "; retrying while window remains",
                          provisional=True)
        # Space out child retries: a fast-crashing child (probe ok,
        # init dies in seconds) must not burn every attempt in the first
        # two minutes of the budget.
        if children < max_children:
            delay = [60, 180, 300][min(children - 1, 2)]
            if fits(delay + probe_timeout):
                print(f"[bench] next child attempt in {delay}s",
                      file=sys.stderr)
                time.sleep(delay)
            else:
                break

    if best:
        # Budget/attempts exhausted: the most complete partial snapshot
        # still beats a CPU fallback.
        _disarm()
        _emit_tpu(best[1], best[2], best[3])
        return 0
    phase["name"] = "cpu fallback"

    # Unrecoverable TPU failure: labeled CPU fallback so the round still
    # records a live number plus the TPU diagnostics. An outer watcher
    # (tools/tpu_battery.sh) disables the fallback — it re-polls for a
    # live window itself instead of burning the core on a CPU measurement.
    # Exit code is 0 whenever a final line was emitted: consumers judge
    # quality by backend/partial fields, not rc.
    if os.environ.get("BENCH_CPU_FALLBACK", "1") == "0":
        _disarm()
        _emit({"backend": "none",
               "error": _clip("; ".join(diags))}, None)
        return 0
    if cpu_stash:  # pre-computed during the watch — emit, don't re-run
        _disarm()
        _emit_cpu(cpu_stash, "")
        return 0
    # Admit the last-resort CPU child only with a realistic budget — a
    # CPU measurement needs minutes (jax import + compile), so a ~60s cap
    # just guarantees a timeout-killed child that wastes the budget tail.
    if fits(min(cpu_timeout, 600)):
        print("[bench] TPU unavailable — CPU fallback", file=sys.stderr)
        from __graft_entry__ import _cpu_env
        eff_cpu = int(min(cpu_timeout, headroom()))
        rc, out = _run([sys.executable, me, "--child", "cpu"], _cpu_env(1),
                       eff_cpu)
        sys.stderr.write(out)
        result = _parse_result(out)
        if result:
            _disarm()
            _emit_cpu(_salvage(result, rc,
                               f"cpu child rc={rc} after {eff_cpu}s "
                               f"budget"), "")
            return 0
        diags.append(f"cpu child: rc={rc}, tail="
                     + " | ".join(out.strip().splitlines()[-3:]))
    _disarm()
    _emit({"backend": "none", "error": _clip("; ".join(diags))}, None)
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        run_child(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--sweep":
        # Per-knob autotune sweep (tpu_resnet/tools/sweep.py): budgeted
        # child per point, resumable, complete RESULT_JSON trajectory —
        # the MFU campaign's knob rig. Like the parent orchestrator,
        # this path never imports jax in-process.
        from tpu_resnet.tools.sweep import main as sweep_main
        sys.exit(sweep_main(sys.argv[2:]))
    else:
        sys.exit(main())
