"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: ResNet-50 CIFAR-10 training steps/sec at global batch 128
on the available chips — directly comparable to the reference's published
'local' number: 13.94 steps/s, README.md:28 (BASELINE.md row 1), which is
``vs_baseline``'s denominator.

The measured step is the full training step: on-device augmentation
(pad/crop/flip/standardize), bf16 forward/backward, L2-in-loss, momentum
update, BN stats update — i.e. what the reference's
``mon_sess.run(train_op)`` covered (resnet_cifar_train.py:343-344), input
included. The input edge is the framework's device-resident path
(tpu_resnet/data/device_data.py): the training split lives in HBM, batches
are cut on-device, and ``train.steps_per_call`` steps run per dispatch —
the same configuration a real CIFAR training run uses by default.
CIFAR-shaped synthetic data is used so the benchmark needs no dataset
download; the compute path is identical.
"""

import json
import time

BASELINE_STEPS_PER_SEC = 13.94  # reference README.md:28


def main():
    import jax
    import jax.numpy as jnp

    from tpu_resnet.config import load_config
    from tpu_resnet import parallel
    from tpu_resnet.data import cifar as cifar_data
    from tpu_resnet.data import device_data
    from tpu_resnet.data.augment import get_augment_fns
    from tpu_resnet.models import build_model
    from tpu_resnet.train import build_schedule, init_state
    from tpu_resnet.train.step import make_train_step

    cfg = load_config("cifar10")
    cfg.data.dataset = "synthetic"
    cfg.train.global_batch_size = 128
    cfg.model.resnet_size = 50
    cfg.model.compute_dtype = "bfloat16"
    k = cfg.train.steps_per_call  # 10: fused steps per dispatch

    mesh = parallel.create_mesh(cfg.mesh)
    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    rng = jax.random.PRNGKey(0)
    state = init_state(model, cfg.optim, sched, rng,
                       jnp.zeros((1, 32, 32, 3)))
    state = jax.device_put(state, parallel.replicated(mesh))

    # CIFAR-10-sized synthetic split, resident in HBM like a real run.
    images, labels = cifar_data.synthetic_data(50_000, 32, 10)
    ds = device_data.DeviceDataset(mesh, images, labels,
                                   cfg.train.global_batch_size, seed=0)
    augment_fn, _ = get_augment_fns("cifar10")
    run_chunk = device_data.compile_resident_steps(
        make_train_step(model, cfg.optim, sched, 10, augment_fn,
                        base_rng=rng, mesh=mesh), ds, mesh, k)

    warmup_chunks, measure_chunks = 4, 30
    step = 0
    for _ in range(warmup_chunks):
        state, metrics = run_chunk(state, step, k)
        step += k
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(measure_chunks):
        state, metrics = run_chunk(state, step, k)
        step += k
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    sps = measure_chunks * k / dt
    print(json.dumps({
        "metric": "cifar10_resnet50_train_steps_per_sec_b128",
        "value": round(sps, 2),
        "unit": "steps/sec",
        "vs_baseline": round(sps / BASELINE_STEPS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
