"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: ResNet-50 CIFAR-10 training steps/sec at global batch 128
on the available chips — directly comparable to the reference's published
'local' number: 13.94 steps/s, README.md:28 (BASELINE.md row 1), which is
``vs_baseline``'s denominator.

The measured step is the full training step: on-device augmentation
(pad/crop/flip/standardize), bf16 forward/backward, L2-in-loss, momentum
update, BN stats update — i.e. what the reference's
``mon_sess.run(train_op)`` covered (resnet_cifar_train.py:343-344), input
pipeline included (synthetic CIFAR-shaped data so the benchmark needs no
dataset download; the host pipeline path is identical).
"""

import json
import time

BASELINE_STEPS_PER_SEC = 13.94  # reference README.md:28


def main():
    import jax

    from tpu_resnet.config import load_config
    from tpu_resnet import parallel
    from tpu_resnet.data import cifar as cifar_data
    from tpu_resnet.data import pipeline
    from tpu_resnet.data.augment import get_augment_fns
    from tpu_resnet.models import build_model
    from tpu_resnet.train import build_schedule, init_state
    from tpu_resnet.train.step import make_train_step, shard_step
    import jax.numpy as jnp

    cfg = load_config("cifar10")
    cfg.data.dataset = "synthetic"
    cfg.data.train_examples  # synthetic: 1024 examples
    cfg.train.global_batch_size = 128
    cfg.model.resnet_size = 50
    cfg.model.compute_dtype = "bfloat16"

    mesh = parallel.create_mesh(cfg.mesh)
    model = build_model(cfg)
    sched = build_schedule(cfg.optim, cfg.train)
    rng = jax.random.PRNGKey(0)
    state = init_state(model, cfg.optim, sched, rng,
                       jnp.zeros((1, 32, 32, 3)))
    state = jax.device_put(state, parallel.replicated(mesh))

    augment_fn, _ = get_augment_fns("cifar10")
    step_fn = shard_step(
        make_train_step(model, cfg.optim, sched, 10, augment_fn,
                        base_rng=rng, mesh=mesh), mesh)

    images, labels = cifar_data.synthetic_data(1024, 32, 10)
    local_bs = parallel.local_batch_size(cfg.train.global_batch_size, mesh)
    batcher = pipeline.ShardedBatcher(images, labels, local_bs, seed=0)
    it = pipeline.device_prefetch(
        pipeline.BackgroundIterator(iter(batcher)),
        parallel.batch_sharding(mesh))

    warmup, measure = 20, 200
    for _ in range(warmup):
        img, lab = next(it)
        state, metrics = step_fn(state, img, lab)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(measure):
        img, lab = next(it)
        state, metrics = step_fn(state, img, lab)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    sps = measure / dt
    print(json.dumps({
        "metric": "cifar10_resnet50_train_steps_per_sec_b128",
        "value": round(sps, 2),
        "unit": "steps/sec",
        "vs_baseline": round(sps / BASELINE_STEPS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
